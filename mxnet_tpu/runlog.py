"""Durable run ledger: append-only JSONL of structured run events.

Everything the observability stack computes today — health verdicts,
anomaly trips, program registrations with atlas digests, serving
``/healthz`` transitions, bench results — evaporates with the process.
This module is the durable record: one JSONL file per process, one JSON
object per line, every line stamped with a shared **run id**, a
monotonically increasing per-process ``seq``, and the process's
rank/role, so the ledgers of a multi-process run merge into a single
ordered timeline (:func:`merge`) and ``tools/sentinel.py`` can replay
the bench trajectory mechanically.

Write discipline: a line is serialized *outside* the ledger lock, then
appended with a single ``write()+flush`` on an ``O_APPEND`` stream —
POSIX keeps concurrent same-file appends line-atomic, and a torn final
line (power loss) damages only itself: readers skip unparseable lines.
Rotation (``MXNET_RUNLOG_MAX_BYTES``, default 8 MiB) atomically
``os.replace``-renames the full file to ``<path>.1`` and starts fresh.
A ledger write must never take training down: failures increment
``runlog_write_errors_total`` and drop the event.

Activation: off by default.  Set ``MXNET_RUNLOG_DIR`` (per-process file
name derived from role/rank/pid — safe for dist launches sharing one
directory) or ``MXNET_RUNLOG_PATH`` (exact file — single process only),
or call :func:`enable` programmatically.  On enable, a ``run_start``
event snapshots argv and the MXNET_*/DMLC_*/JAX_* environment including
the step cache-key env flags (``executor.STEP_ENV_KEYS``).

Device topology is recorded *lazily* (:func:`note_topology`, called
from ``health.register_program`` and ``bench.py``): touching
``jax.devices()`` at import/enable time would initialize the backend
before test/apps configure platforms.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from .base import get_env
from . import telemetry as _telemetry

__all__ = ["enable", "disable", "enabled", "event", "run_id", "path",
           "note_topology", "merge", "RunLog"]

_EVENTS = _telemetry.counter(
    "runlog_events_total", "events appended to the run ledger",
    labelnames=("event",))
_WRITE_ERRORS = _telemetry.counter(
    "runlog_write_errors_total",
    "ledger events dropped because the append failed")

#: env prefixes worth snapshotting at run start (config surface of the
#: runtime + launcher + jax, nothing secret-bearing).
_ENV_PREFIXES = ("MXNET_", "DMLC_", "JAX_", "XLA_")


def _gen_run_id() -> str:
    return "%x-%d-%04x" % (int(time.time()), os.getpid(),
                           int.from_bytes(os.urandom(2), "big"))


def _env_snapshot() -> Dict[str, str]:
    snap = {k: v for k, v in os.environ.items()
            if k.startswith(_ENV_PREFIXES)}
    # the step cache-key flags are part of the snapshot even when unset:
    # "unset" is itself a config state the sentinel may need to compare.
    try:
        from .executor import STEP_ENV_KEYS
        keys = tuple(STEP_ENV_KEYS)
    except Exception:
        # executor may not be importable yet (ledger enabled during
        # package init); fall back to the known cache-key flags.
        keys = ("MXNET_TPU_FUSED_STEP", "MXNET_TPU_MESH_STEP")
    # program-cache location/size join for the same reason: a warm deploy
    # and a cold one differ ONLY in these (plus the artifacts on disk)
    keys = keys + ("MXNET_PROGRAM_CACHE_DIR", "MXNET_PROGRAM_CACHE_MAX_BYTES")
    for k in keys:
        snap.setdefault(k, os.environ.get(k, ""))
    return snap


class RunLog:
    """One process's append-only JSONL ledger.

    Each line: ``{"ts": unix_s, "run_id", "seq", "rank", "role",
    "event": <type>, ...payload}``.  ``seq`` orders events within one
    process even when wall clocks tie; (ts, run_id, seq) orders the
    merged multi-process timeline.
    """

    def __init__(self, path: str, run_id: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        self._path = path
        self._run_id = run_id or os.environ.get("MXNET_RUN_ID") \
            or _gen_run_id()
        self._max_bytes = (get_env("MXNET_RUNLOG_MAX_BYTES",
                                   8 * 1024 * 1024, int)
                           if max_bytes is None else int(max_bytes))
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = None
        self._rank = os.environ.get("DMLC_WORKER_ID", "0")
        self._role = os.environ.get("DMLC_ROLE", "local")

    @property
    def path(self) -> str:
        return self._path

    @property
    def run_id(self) -> str:
        return self._run_id

    def _open(self):
        d = os.path.dirname(self._path)
        if d:
            os.makedirs(d, exist_ok=True)
        # O_APPEND via mode "a": concurrent appends land whole-line.
        self._fh = open(self._path, "a", encoding="utf-8")

    def _rotate_locked(self):
        try:
            if self._fh is not None:
                self._fh.close()
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass
        self._fh = None

    def event(self, event_type: str, **payload) -> bool:
        """Append one event; returns False (and counts the drop) on any
        failure.  Serialization happens before the lock; the locked
        region is seq assignment + one write."""
        rec = {"ts": round(time.time(), 6), "run_id": self._run_id,
               "rank": self._rank, "role": self._role,
               "event": str(event_type)}
        for k, v in payload.items():
            if k not in rec:
                rec[k] = v
        try:
            with self._lock:
                rec["seq"] = self._seq
                self._seq += 1
                line = json.dumps(rec, default=str) + "\n"
                if self._fh is None:
                    self._open()
                if self._max_bytes and \
                        self._fh.tell() + len(line) > self._max_bytes:
                    self._rotate_locked()
                    self._open()
                self._fh.write(line)
                self._fh.flush()
        except Exception:
            _WRITE_ERRORS.inc()
            return False
        _EVENTS.labels(event=str(event_type)).inc()
        return True

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:
                    pass
                self._fh = None


# ---------------------------------------------------------------------------
# module-level ledger (the one the built-in hooks write to)
# ---------------------------------------------------------------------------
_log: Optional[RunLog] = None
_state_lock = threading.Lock()
_topology_noted = False


def _default_path() -> Optional[str]:
    explicit = os.environ.get("MXNET_RUNLOG_PATH")
    if explicit:
        return explicit
    directory = os.environ.get("MXNET_RUNLOG_DIR")
    if not directory:
        return None
    role = os.environ.get("DMLC_ROLE", "local")
    rank = os.environ.get("DMLC_WORKER_ID", "0")
    return os.path.join(directory,
                        "runlog_%s%s_%d.jsonl" % (role, rank, os.getpid()))


def enable(path: Optional[str] = None,
           run_id: Optional[str] = None) -> Optional[RunLog]:
    """Open the process ledger and write the ``run_start`` event.
    Idempotent (returns the existing ledger if already enabled); returns
    None when no path is given and no env var names one."""
    global _log, _topology_noted
    with _state_lock:
        if _log is not None:
            return _log
        p = path or _default_path()
        if not p:
            return None
        _log = RunLog(p, run_id=run_id)
        _topology_noted = False
        log = _log
    # cache identity without forcing jax backend init: dir comes from the
    # env; the fingerprint is known only once program_cache.enable() ran
    # (which then also logs a full "program_cache_start" event)
    from . import program_cache as _program_cache
    log.event("run_start",
              argv=list(sys.argv),
              env=_env_snapshot(),
              python="%d.%d.%d" % sys.version_info[:3],
              pid=os.getpid(),
              program_cache_dir=os.environ.get("MXNET_PROGRAM_CACHE_DIR"),
              program_cache_fingerprint=_program_cache.fingerprint())
    return log


def disable():
    """Write ``run_end`` and close the ledger.  Idempotent."""
    global _log
    with _state_lock:
        log, _log = _log, None
    if log is not None:
        log.event("run_end")
        log.close()


def enabled() -> bool:
    return _log is not None


def run_id() -> Optional[str]:
    log = _log
    return log.run_id if log is not None else None


def path() -> Optional[str]:
    log = _log
    return log.path if log is not None else None


def event(event_type: str, **payload) -> bool:
    """Append to the process ledger; no-op (False) when disabled."""
    log = _log
    if log is None:
        return False
    return log.event(event_type, **payload)


def note_topology() -> bool:
    """Record the device topology once per ledger.  Deferred from
    enable() on purpose: calling ``jax.devices()`` at import time would
    initialize the backend before callers configure platforms — this is
    invoked from the first ``health.register_program`` and from bench.py,
    both safely after jax is in use."""
    global _topology_noted
    log = _log
    if log is None:
        return False
    with _state_lock:
        if _topology_noted:
            return False
        _topology_noted = True
    try:
        import jax
        devs = jax.devices()
        payload = {"platform": devs[0].platform if devs else "none",
                   "n_devices": len(devs),
                   "process_index": getattr(jax, "process_index",
                                            lambda: 0)(),
                   "devices": [str(d) for d in devs[:64]]}
    except Exception as exc:
        payload = {"error": str(exc)}
    return log.event("device_topology", **payload)


# ---------------------------------------------------------------------------
# merge: many per-process ledgers -> one ordered timeline
# ---------------------------------------------------------------------------
def merge(paths: List[str]) -> List[dict]:
    """Merge ledger files into one timeline ordered by (ts, run_id, seq,
    source).  Unparseable lines (torn tails) are skipped, not fatal —
    the whole point of line-framed JSONL.  Each record gains a
    ``source`` field naming the file it came from."""
    records = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        rec.setdefault("source", os.path.basename(p))
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("ts", 0.0), str(r.get("run_id", "")),
                                r.get("seq", 0), str(r.get("source", ""))))
    return records


def main(argv=None):
    """CLI: ``python -m mxnet_tpu.runlog merge <files...>`` prints the
    merged timeline as JSONL on stdout."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] != "merge" or len(argv) < 2:
        sys.stderr.write(
            "usage: python -m mxnet_tpu.runlog merge FILE [FILE...]\n")
        return 2
    for rec in merge(argv[1:]):
        sys.stdout.write(json.dumps(rec) + "\n")
    return 0


if get_env("MXNET_RUNLOG_DIR", None) or get_env("MXNET_RUNLOG_PATH", None):
    enable()


if __name__ == "__main__":
    sys.exit(main())
