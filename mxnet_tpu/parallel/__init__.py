"""Parallelism & distribution: meshes, shardings, collectives, train steps.

This is the TPU-native replacement for the reference's distribution stack
(SURVEY.md §2.2, §5.8): instead of NCCL reduce trees + a ps-lite parameter
server, everything is XLA collectives over an ICI/DCN device mesh driven by
``pjit``/``shard_map``.
"""
from .mesh import (make_mesh, data_parallel_sharding, replicated_sharding,
                   ShardingRules)
from .comm import ProcessGroup, process_group, init_distributed
from .data_parallel import DataParallelTrainer, dp_train_step
from . import tensor_parallel
from . import ring_attention
from . import pipeline
from .pipeline import Pipeline, pipeline_apply
from . import moe
from .moe import moe_ffn, top_k_gating, init_moe_params
from . import elastic
from .elastic import ElasticRunner, run_elastic
