"""Data-parallel (and DP×TP) training over a device mesh.

Reference analog: DataParallelExecutorGroup slicing batches across GPUs +
KVStore gradient reduce (SURVEY.md §3.1, module/executor_group.py:28-80).
TPU-native: ONE jitted SPMD train step over a Mesh — inputs sharded on the
``dp`` axis, parameters sharded per ShardingRules (replicated for pure DP,
megatron splits for TP) — XLA inserts the gradient all-reduce over ICI
automatically from the sharding annotations.  No per-parameter push/pull:
the whole step (fwd+bwd+optimizer) is one XLA program with donated buffers.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError, AttrDict
from .. import health as _health
from .mesh import ShardingRules

__all__ = ["dp_train_step", "DataParallelTrainer"]


def _sgd_mom(p, g, m, lr, momentum, wd):
    g = g + wd * p
    m2 = momentum * m - lr * g
    return p + m2, m2


def dp_train_step(loss_fn: Callable, mesh: Mesh,
                  rules: Optional[ShardingRules] = None,
                  lr=0.01, momentum=0.9, wd=0.0, dp_axis="dp"):
    """Build a jitted SPMD step for a pure ``loss_fn(params, batch) -> loss``.

    params replicated (or sharded per `rules`), batch sharded on `dp_axis`.
    Returns step(params, moms, batch) -> (params, moms, loss).
    """
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(dp_axis))

    def shard_param(name, x):
        if rules is None:
            return repl
        return rules.sharding_for(name, x.shape)

    @partial(jax.jit, donate_argnums=(0, 1))
    def _step(params, moms, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_p, new_m = {}, {}
        for k in params:
            new_p[k], new_m[k] = _sgd_mom(params[k], grads[k], moms[k],
                                          lr, momentum, wd)
        return new_p, new_m, loss

    first = {"run": True}

    def step(params, moms, batch):
        # donated-buffer health accounting on the first execution only:
        # params/moms here are the OLD donated inputs — handing them to
        # audit_donation right after dispatch surfaces an alias XLA
        # silently dropped (program_donation_leaks_total)
        first_run = first["run"]
        first["run"] = False
        if first_run and _health.enabled:
            _health.register_program("dp_train_step", _step,
                                     (params, moms, batch), donated=True)
        out = _step(params, moms, batch)
        if first_run and _health.enabled:
            _health.audit_donation("dp_train_step", (params, moms))
        return out

    def place(params, moms, batch_example=None):
        p = {k: jax.device_put(v, shard_param(k, v)) for k, v in params.items()}
        m = {k: jax.device_put(v, shard_param(k, v)) for k, v in moms.items()}
        return p, m

    step.place = place
    step.batch_sharding = batch_sh
    return step


class DataParallelTrainer:
    """SPMD trainer for a Symbol graph: the Module-era training loop
    collapsed into one pjit program per step.

    Usage::

        net = sym.SoftmaxOutput(fc2, name='softmax')
        trainer = DataParallelTrainer(net, mesh, loss='softmax_ce',
                                      data_names=('data',),
                                      label_names=('softmax_label',))
        trainer.init_params(data=(B, ...))
        loss = trainer.step({'data': x, 'softmax_label': y})
    """

    def __init__(self, symbol, mesh: Mesh, lr=0.01, momentum=0.9, wd=0.0,
                 data_names=("data",), label_names=("softmax_label",),
                 rules: Optional[ShardingRules] = None, dp_axis="dp",
                 dtype="float32", loss="softmax_ce"):
        from ..executor import _Plan
        self.symbol = symbol
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.rules = rules
        self.dtype = np.dtype(dtype)
        self.data_names = tuple(data_names)
        self.label_names = tuple(label_names)
        self.lr, self.momentum, self.wd = lr, momentum, wd
        self.loss_kind = loss
        self._plan = _Plan(symbol, train=True)
        self.param_names = [n for n in self._plan.arg_names
                            if n not in self.data_names + self.label_names]
        self.aux_names = list(self._plan.aux_names)
        self.params: Dict[str, Any] = {}
        self.moms: Dict[str, Any] = {}
        self.aux: Dict[str, Any] = {}
        self._step = None
        # the batch sharding never changes for a trainer: build it once
        # instead of per step
        self._batch_sharding = NamedSharding(mesh, P(dp_axis))

    # -- initialization ---------------------------------------------------
    def init_params(self, initializer=None, **data_shapes):
        from .. import initializer as init_mod
        from .. import ndarray as nd
        initializer = initializer or init_mod.Xavier()
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**data_shapes)
        shapes = dict(zip(self._plan.arg_names, arg_shapes))
        for n in self.param_names:
            arr = nd.zeros(shapes[n], dtype=self.dtype)
            initializer(init_mod.InitDesc(n), arr)
            self.params[n] = arr._data
            self.moms[n] = jnp.zeros_like(arr._data)
        for n, s in zip(self.aux_names, aux_shapes):
            arr = nd.zeros(s, dtype=np.float32)
            initializer(init_mod.InitDesc(n), arr)
            self.aux[n] = arr._data
        self._place()
        return self

    def _place(self):
        repl = NamedSharding(self.mesh, P())

        def sh(name, x):
            if self.rules is None:
                return repl
            return self.rules.sharding_for(name, x.shape)

        self.params = {k: jax.device_put(v, sh(k, v))
                       for k, v in self.params.items()}
        self.moms = {k: jax.device_put(v, sh(k, v))
                     for k, v in self.moms.items()}
        self.aux = {k: jax.device_put(v, repl) for k, v in self.aux.items()}

    # -- the loss over the symbolic plan ----------------------------------
    def _loss_fn(self, params, aux, batch, keys):
        arg_vals = dict(params)
        for n in self.data_names + self.label_names:
            arg_vals[n] = batch[n]
        outs, new_aux = self._plan.execute(arg_vals, aux, keys)
        out = outs[0]
        if self.loss_kind == "softmax_ce":
            # symbol's final op is SoftmaxOutput: out is softmax probs;
            # CE loss on the label gives identical grads to the reference's
            # implicit (p - onehot) path, with a real loss value to report.
            label = batch[self.label_names[0]].astype(jnp.int32)
            logp = jnp.log(jnp.maximum(out, 1e-30))
            # flatten all leading axes (batch, and time for sequence
            # outputs) so every position contributes to the loss, matching
            # the reference's per-position SoftmaxOutput gradient
            logp2 = logp.reshape(-1, logp.shape[-1])
            picked = jnp.take_along_axis(
                logp2, label.reshape(-1, 1), axis=1)
            loss = -jnp.mean(picked)
        else:
            loss = jnp.mean(out)
        return loss, new_aux

    def _build_step(self):
        lr, momentum, wd = self.lr, self.momentum, self.wd
        n_rng = self._plan.n_rng

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, moms, aux, batch, keys):
            (loss, new_aux), grads = jax.value_and_grad(
                lambda p: self._loss_fn(p, aux, batch, keys),
                has_aux=True)(params)
            new_p, new_m = {}, {}
            for k in params:
                new_p[k], new_m[k] = _sgd_mom(params[k], grads[k], moms[k],
                                              lr, momentum, wd)
            return new_p, new_m, {k: new_aux[k] for k in aux}, loss

        return step

    def step(self, batch: Dict[str, Any]):
        from .. import random as _random
        first_run = self._step is None
        if first_run:
            self._step = self._build_step()
        bsh = self._batch_sharding
        b = {}
        for k, v in batch.items():
            # adopt device-resident NDArrays directly — no asnumpy host
            # bounce; host values upload once here
            data = getattr(v, "_data", None)
            if data is None:
                data = jnp.asarray(v)
            if getattr(data, "sharding", None) != bsh:
                data = jax.device_put(data, bsh)
            b[k] = data
        keys = jnp.stack([_random.next_key()
                          for _ in range(max(1, self._plan.n_rng))])
        # keep refs to the donated inputs across the first dispatch so the
        # health layer can verify XLA actually aliased them
        donated = (self.params, self.moms, self.aux)
        if first_run and _health.enabled:
            _health.register_program("dp_step", self._step,
                                     donated + (b, keys), donated=True)
        self.params, self.moms, self.aux, loss = \
            self._step(self.params, self.moms, self.aux, b, keys)
        if first_run and _health.enabled:
            _health.audit_donation("dp_step", donated)
        return loss

    def get_params(self):
        """Return params as NDArrays (gathered) for checkpointing."""
        from ..ndarray.ndarray import NDArray
        from ..context import current_context
        ctx = current_context()
        return ({k: NDArray(jnp.asarray(v), ctx)
                 for k, v in self.params.items()},
                {k: NDArray(jnp.asarray(v), ctx)
                 for k, v in self.aux.items()})
