"""Pipeline parallelism: GPipe-style microbatching over mesh stages.

Beyond-parity feature (SURVEY.md §2.2: the reference has no pipeline
parallelism; the plan's phase-5+ stretch goal).  TPU-native design: stages
are sharded onto a ``pp`` mesh axis; the schedule is a ``lax.scan`` over
microbatches with a ``ppermute`` shift of activations between stage
neighbours each tick — the classic GPipe fill/drain pipeline expressed as
ONE compiled SPMD program (no host orchestration per tick).

Usage::

    mesh = make_mesh({"pp": 4})
    pp = Pipeline(stage_fn, num_stages=4, num_microbatches=8)
    out = pp(params_per_stage, x)        # inside shard_map over "pp"
    # or end-to-end:
    y = pipeline_apply(mesh, "pp", stage_fn, stage_params, x, n_micro=8)

``stage_fn(params, x) -> x`` is the per-stage computation; all stages must
share one activation shape (pad/project at stage boundaries otherwise).
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["Pipeline", "pipeline_apply"]


class Pipeline:
    """The inner SPMD pipeline body (call inside shard_map over the pp
    axis)."""

    def __init__(self, stage_fn: Callable, num_stages: int,
                 num_microbatches: int, axis: str = "pp"):
        if num_microbatches < 1:
            raise ValueError("need at least one microbatch")
        self.stage_fn = stage_fn
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.axis = axis

    def __call__(self, stage_params, micro_in):
        """stage_params: this stage's params (already sharded);
        micro_in: (num_microbatches, mb, ...) microbatches, meaningful on
        stage 0.  Returns (num_microbatches, mb, ...) outputs, meaningful
        on the last stage."""
        s = self.num_stages
        m = self.num_microbatches
        stage_id = lax.axis_index(self.axis)
        ticks = m + s - 1
        mb_shape = micro_in.shape[1:]

        def tick(carry, t):
            outputs, prev_act = carry
            # stage 0 injects microbatch t (when still filling); others
            # consume the activation shifted from the left neighbour
            inj = micro_in[jnp.minimum(t, m - 1)]
            x = jnp.where(stage_id == 0, inj, prev_act)
            y = self.stage_fn(stage_params, x)
            # the last stage banks its finished microbatch (t - (s-1))
            out_idx = t - (s - 1)
            bank = (stage_id == s - 1) & (out_idx >= 0)
            slot = jnp.clip(out_idx, 0, m - 1)
            outputs = outputs.at[slot].set(
                jnp.where(bank, y, outputs[slot]))
            # shift activations one stage to the right over ICI
            nxt = lax.ppermute(y, self.axis,
                               [(i, (i + 1) % s) for i in range(s)])
            return (outputs, nxt), None

        outputs0 = jnp.zeros((m,) + mb_shape, micro_in.dtype)
        prev0 = jnp.zeros(mb_shape, micro_in.dtype)
        # carries vary per stage: mark them device-varying for shard_map
        from ._compat import pvary
        outputs0, prev0 = pvary((outputs0, prev0), (self.axis,))
        (outputs, _), _ = lax.scan(tick, (outputs0, prev0),
                                   jnp.arange(ticks))
        return outputs


def pipeline_apply(mesh, axis: str, stage_fn: Callable, stage_params,
                   x, n_micro: int):
    """End-to-end GPipe forward: split x into microbatches, run the
    pipeline over ``mesh[axis]`` stages, gather the last stage's outputs.

    stage_params: pytree whose leaves have a leading stage axis of length
    ``num_stages`` (leaf shape (S, ...)); each stage sees its own slice.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ._compat import shard_map

    s = mesh.shape[axis]
    n = x.shape[0]
    if n % n_micro:
        raise ValueError("batch %d not divisible by %d microbatches"
                         % (n, n_micro))
    micro = x.reshape((n_micro, n // n_micro) + x.shape[1:])
    pipe = Pipeline(stage_fn, s, n_micro, axis)

    def body(params_slice, micro_all):
        # params_slice arrives with a leading length-1 stage axis
        my_params = jax.tree_util.tree_map(lambda p: p[0], params_slice)
        outs = pipe(my_params, micro_all)
        # only the last stage's bank is meaningful: keep it, zero others,
        # then psum so every stage returns the final outputs
        keep = (lax.axis_index(axis) == s - 1).astype(outs.dtype)
        return lax.psum(outs * keep, axis)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis), P()), out_specs=P())
    out = fn(stage_params, micro)
    return out.reshape((n,) + out.shape[2:])
