"""Expert parallelism: Mixture-of-Experts layer sharded over a mesh axis.

Reference analog: none — the reference (2018) predates MoE; SURVEY.md §2.2
lists expert parallelism as the one optional strategy.  TPU-native design:
experts live sharded over the ``ep`` mesh axis; tokens are routed with a
top-k softmax gate and exchanged via ``all_to_all`` over ICI (the standard
GShard/Switch dispatch), with fixed expert capacity so every shape is
static for XLA.

Layout (per shard_map block, E experts over ``n`` chips, local E_l = E/n):
  1. gate: (T, E) logits -> top-k expert ids + combine weights
  2. dispatch: scatter tokens into a (E, C) capacity buffer (C tokens per
     expert; overflow dropped, the Switch-Transformer behavior)
  3. all_to_all: (E, C, D) -> (E_l, n*C, D) — each chip keeps only its
     local experts' slots but receives them from every chip
  4. expert FFN on the local (E_l, n*C, D) batch — dense matmuls on MXU
  5. all_to_all back + weighted combine into (T, D)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["top_k_gating", "moe_ffn", "MoEParams", "init_moe_params"]


def top_k_gating(logits, k: int):
    """Top-k softmax gate (GShard style): returns (weights, ids) with
    weights renormalized over the chosen k."""
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights, ids = jax.lax.top_k(gates, k)
    weights = weights / jnp.maximum(
        weights.sum(axis=-1, keepdims=True), 1e-9)
    return weights.astype(logits.dtype), ids


def _dispatch_mask(ids, weights, num_experts: int, capacity: int):
    """(T, k) routed ids -> dispatch one-hot (T, E, C) and combine weights.

    Position within each expert's capacity buffer is the token's rank among
    tokens routed to that expert (cumsum trick); tokens past capacity are
    dropped (their combine weight is zeroed) — static shapes throughout.
    """
    T, k = ids.shape
    flat_ids = ids.reshape(-1)                             # (T*k,)
    onehot = jax.nn.one_hot(flat_ids, num_experts,
                            dtype=jnp.int32)               # (T*k, E)
    pos = jnp.cumsum(onehot, axis=0) - 1                   # rank per expert
    pos = jnp.sum(pos * onehot, axis=-1)                   # (T*k,)
    keep = pos < capacity
    disp = (jax.nn.one_hot(flat_ids, num_experts, dtype=jnp.float32)
            [:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                             dtype=jnp.float32)[:, None, :])
    disp = disp * keep[:, None, None].astype(jnp.float32)
    disp = disp.reshape(T, k, num_experts, capacity)
    w = weights.reshape(T, k, 1, 1).astype(jnp.float32)
    combine = jnp.sum(disp * w, axis=1)                    # (T, E, C)
    dispatch = jnp.sum(disp, axis=1)                       # (T, E, C)
    return dispatch, combine


class MoEParams:
    """Dense parameter bundle for an MoE FFN: gate + per-expert weights."""

    def __init__(self, wg, w1, w2):
        self.wg = wg      # (D, E)
        self.w1 = w1      # (E, D, H)
        self.w2 = w2      # (E, H, D)


def init_moe_params(rng: np.random.RandomState, d_model: int,
                    d_hidden: int, num_experts: int,
                    dtype=np.float32) -> MoEParams:
    s1 = 1.0 / np.sqrt(d_model)
    s2 = 1.0 / np.sqrt(d_hidden)
    return MoEParams(
        jnp.asarray(rng.uniform(-s1, s1, (d_model, num_experts))
                    .astype(dtype)),
        jnp.asarray(rng.uniform(-s1, s1,
                                (num_experts, d_model, d_hidden))
                    .astype(dtype)),
        jnp.asarray(rng.uniform(-s2, s2,
                                (num_experts, d_hidden, d_model))
                    .astype(dtype)))


def moe_ffn(x, params: MoEParams, mesh: Optional[Mesh] = None,
            axis: str = "ep", k: int = 2,
            capacity_factor: float = 1.25, act=jax.nn.relu):
    """MoE FFN layer: top-k routed expert MLPs.

    x: (T, D) tokens (flatten batch x seq first).  With ``mesh`` given,
    experts are sharded over mesh axis ``axis`` and tokens exchanged with
    two ``all_to_all`` collectives (expert parallelism over ICI); without
    a mesh, computes all experts locally (single-chip reference behavior,
    used by tests as ground truth).
    """
    E = params.wg.shape[1]
    T = x.shape[0]

    def gate_and_dispatch(xs, capacity):
        logits = xs @ params.wg.astype(xs.dtype)
        weights, ids = top_k_gating(logits, k)
        dispatch, combine = _dispatch_mask(ids, weights, E, capacity)
        # (E, C, D) expert inputs
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(xs.dtype), xs)
        return expert_in, combine

    def expert_mlp(expert_in, w1, w2):
        h = act(jnp.einsum("ecd,edh->ech", expert_in,
                           w1.astype(expert_in.dtype)))
        return jnp.einsum("ech,ehd->ecd", h, w2.astype(expert_in.dtype))

    if mesh is None:
        capacity = int(np.ceil(capacity_factor * k * T / E))
        expert_in, combine = gate_and_dispatch(x, capacity)
        expert_out = expert_mlp(expert_in, params.w1, params.w2)
        return jnp.einsum("tec,ecd->td", combine.astype(x.dtype),
                          expert_out)

    from ._compat import shard_map
    n = mesh.shape[axis]
    if E % n:
        raise ValueError("num_experts %d not divisible by %s=%d"
                         % (E, axis, n))
    # capacity is per chip: each shard dispatches its T/n local tokens, so
    # the slot budget must scale with the LOCAL token count or
    # capacity_factor silently inflates n-fold (and buffers with it)
    local_capacity = int(np.ceil(capacity_factor * k * (T // n) / E))

    def sharded(xs, w1_local, w2_local):
        # xs: (T/n, D) local tokens; w*_local: (E/n, ...) local experts
        expert_in, combine = gate_and_dispatch(xs, local_capacity)
        # exchange: every chip sends each expert's slots to its owner;
        # axis 0 splits experts, concat on capacity
        expert_in = jax.lax.all_to_all(expert_in, axis, split_axis=0,
                                       concat_axis=1, tiled=True)
        expert_out = expert_mlp(expert_in, w1_local, w2_local)
        expert_out = jax.lax.all_to_all(expert_out, axis, split_axis=1,
                                        concat_axis=0, tiled=True)
        return jnp.einsum("tec,ecd->td", combine.astype(xs.dtype),
                          expert_out)

    f = shard_map(sharded, mesh=mesh,
                  in_specs=(P(axis, None), P(axis, None, None),
                            P(axis, None, None)),
                  out_specs=P(axis, None))
    return f(x, params.w1, params.w2)


def load_balancing_loss(logits, ids, num_experts: int):
    """Switch-Transformer auxiliary load-balancing loss: E * sum_e
    (fraction of tokens routed to e) * (mean gate prob of e)."""
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    me = gates.mean(axis=0)
    ce = jnp.mean(jax.nn.one_hot(ids[:, 0], num_experts,
                                 dtype=jnp.float32), axis=0)
    return num_experts * jnp.sum(me * ce)


__all__.append("load_balancing_loss")
