"""Elastic training: failure detection + checkpoint-based restart.

Reference analog: essentially absent in the reference (SURVEY.md §5.3 —
ps-lite gives node roles but no in-tree elastic recovery; the documented
story is "reload last epoch checkpoint manually", common/fit.py:56-66).
This module goes beyond parity with a torchelastic-style supervisor for
TPU training:

  * :class:`ElasticRunner` — a single-host supervisor that launches N
    worker processes (a fake cluster, the tests/nightly pattern; on real
    pods the same contract is fulfilled by the cluster scheduler), detects
    worker death, and relaunches the *whole* gang with a bumped restart
    generation.  Synchronous SPMD training cannot survive losing a member
    (every collective is global), so gang restart + resume is the correct
    semantic — the same decision torchelastic made.
  * :func:`run_elastic` — the worker-side loop: restore the latest
    checkpoint if one exists, run ``train_fn`` from that step, write
    periodic checkpoints via orbax (``mxnet_tpu.checkpoint``).
  * :func:`latest_checkpoint` / :func:`save_step` — step-numbered
    checkpoint bookkeeping shared by both sides.

The supervisor/worker contract is environment-based (MXNET_ELASTIC_*),
mirroring how DMLC_* variables drive the dist kvstore.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

__all__ = ["ElasticRunner", "run_elastic", "latest_checkpoint",
           "save_step"]

_STEP_RE = re.compile(r"^step_(\d+)$")


def save_step(ckpt_dir: str, step: int, params, keep: Optional[int] = None
              ) -> str:
    """Write a step-numbered sharded checkpoint; returns its path.

    The write is two-phase: tensors first, then an atomic commit marker —
    ``latest_checkpoint`` only considers marked directories, so a worker
    killed mid-save can never poison the resume point.  After committing,
    all but the newest ``keep`` committed checkpoints are pruned
    (``MXNET_CKPT_KEEP``, default 3)."""
    from ..base import get_env
    from ..checkpoint import COMMIT_MARKER, save_sharded
    path = os.path.join(ckpt_dir, "step_%d" % step)
    save_sharded(path, params, force=True)
    marker = os.path.join(path, COMMIT_MARKER)
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"step": int(step)}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, marker)
    if keep is None:
        keep = get_env("MXNET_CKPT_KEEP", 3, int)
    if keep and keep > 0:
        committed = sorted(_committed_steps(ckpt_dir))
        for old in committed[:-keep]:
            shutil.rmtree(os.path.join(ckpt_dir, "step_%d" % old),
                          ignore_errors=True)
    return path


def _committed_steps(ckpt_dir: str) -> List[int]:
    from ..checkpoint import COMMIT_MARKER
    steps = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m and os.path.exists(
                os.path.join(ckpt_dir, name, COMMIT_MARKER)):
            steps.append(int(m.group(1)))
    return steps


def latest_checkpoint(ckpt_dir: str):
    """(step, path) of the newest COMMITTED checkpoint, or (None, None).

    Uncommitted directories — a worker died between the tensor write and
    the marker — are skipped, not errors: the previous committed step is
    still a valid resume point."""
    if not os.path.isdir(ckpt_dir):
        return None, None
    committed = _committed_steps(ckpt_dir)
    if not committed:
        return None, None
    best = max(committed)
    return best, os.path.join(ckpt_dir, "step_%d" % best)


def run_elastic(train_fn: Callable, ckpt_dir: str, total_steps: int):
    """Worker-side elastic loop.

    ``train_fn(start_step, total_steps, save, restored)`` runs training
    from ``start_step``; ``restored`` is the params tree of the latest
    checkpoint (None on a fresh start); the loop should call
    ``save(step, params)`` periodically (the closure handles the
    step-numbered directory layout) and return its final params.  On
    entry the latest checkpoint (if any) decides ``start_step`` — a
    relaunched worker resumes instead of restarting from scratch.

    Returns (start_step, final_params).
    """
    from ..checkpoint import load_sharded
    step, path = latest_checkpoint(ckpt_dir)
    start = 0
    restored = None
    if step is not None:
        restored = load_sharded(path)
        start = step

    def save(step, params):
        save_step(ckpt_dir, step, params)

    final = train_fn(start, total_steps, save, restored)
    return start, final


class ElasticRunner:
    """Single-host gang supervisor (fake-cluster pattern).

    Launches ``nworkers`` copies of ``cmd`` with rank env vars, watches
    for failures, and relaunches the whole gang (with
    ``MXNET_ELASTIC_RESTART`` bumped) until the gang exits cleanly or
    ``max_restarts`` is exhausted.  Worker processes coordinate through
    ``jax.distributed``/kvstore exactly as a normal run; recovery state
    travels only through the checkpoint directory.
    """

    def __init__(self, cmd: Sequence[str], nworkers: int,
                 max_restarts: int = 3, env: Optional[dict] = None,
                 poll_interval: float = 0.2, restart_backoff: float = 0.2):
        self.cmd = list(cmd)
        self.nworkers = nworkers
        self.max_restarts = max_restarts
        self.env = dict(env or os.environ)
        self.poll_interval = poll_interval
        self.restart_backoff = restart_backoff
        self.restarts = 0

    def _launch(self) -> List[subprocess.Popen]:
        procs = []
        for rank in range(self.nworkers):
            env = dict(self.env)
            env.update({
                "MXNET_ELASTIC_RANK": str(rank),
                "MXNET_ELASTIC_NWORKERS": str(self.nworkers),
                "MXNET_ELASTIC_RESTART": str(self.restarts),
            })
            procs.append(subprocess.Popen(self.cmd, env=env))
        return procs

    def _reap(self, procs: List[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in procs:
            timeout = max(0.1, deadline - time.time())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def run(self) -> int:
        """Supervise until clean exit; returns total restart count.
        Raises RuntimeError when max_restarts is exhausted."""
        while True:
            procs = self._launch()
            failed = False
            while True:
                codes = [p.poll() for p in procs]
                if any(c not in (None, 0) for c in codes):
                    failed = True  # a member died: the gang is lost
                    break
                if all(c == 0 for c in codes):
                    break
                time.sleep(self.poll_interval)
            if not failed:
                return self.restarts
            cause = "worker_exit_%s" % next(
                (c for c in codes if c not in (None, 0)), "unknown")
            self._reap(procs)
            self.restarts += 1
            if self.restarts > self.max_restarts:
                raise RuntimeError(
                    "elastic training failed: %d restarts exhausted"
                    % self.max_restarts)
            from .. import runlog as _runlog
            _runlog.event("elastic_restart", generation=self.restarts,
                          cause=cause)
            # brief backoff before relaunch: lets the dead gang's sockets
            # leave TIME_WAIT and keeps a crash-looping worker from
            # hot-spinning the supervisor
            time.sleep(min(5.0, self.restart_backoff * (2 ** (
                self.restarts - 1))))
