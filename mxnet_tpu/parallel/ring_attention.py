"""Ring attention: sequence-parallel exact attention over the ICI ring.

Beyond-parity requirement (SURVEY.md §5.7): the reference (2018) has only
bucketing/fused-RNN for long sequences; long-context LM workloads need the
sequence dimension sharded across chips.  Design: K/V blocks rotate around
the mesh ring via ``ppermute`` while each chip holds its Q shard; softmax is
accumulated blockwise with the running-max rescaling trick (flash-attention
style), so attention over sequence length S costs O(S/n) memory per chip and
the K/V transfers ride the ICI ring concurrently with compute.

This module provides:
- ``blockwise_attention``: single-device flash-style blockwise kernel
  building block (jax.lax.scan over K/V blocks; XLA fuses into MXU matmuls).
- ``ring_attention``: shard_map'd ring over a named mesh axis.
- ``ulysses_attention``: all-to-all head-scatter alternative (attention-heavy
  models with many heads: seq-gather/head-scatter costs one all_to_all each
  way instead of (n-1) ring hops).

Trace-time env gate: these entry points consult
``ops.pallas_attention.flash_attention_available`` (the
``MXNET_TPU_PALLAS_ATTN`` kernel gate) when deciding the per-shard
formulation, so the decision is baked into whatever program the caller
traces them into.  The declared cache-key contract covering that read:
``Executor.STEP_ENV_KEYS`` re-specializes every cached step program when
the gate flips, and the ``MultiHeadAttention`` op declares the same keys
in its ``env_keys`` for plan-level programs.  Callers jitting these
functions directly own their own cache and must key it likewise.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["blockwise_attention", "ring_attention", "ulysses_attention"]


def _attn_block(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One (Q-block × K-block) update with running softmax rescaling.

    q: [B,H,Tq,D], k/v: [B,H,Tk,D]; m/l/o carry the running max / sum /
    output accumulator.  fp32 accumulation regardless of input dtype.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    o_new = o_prev * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def blockwise_attention(q, k, v, block_size: int = 512, causal: bool = False,
                        scale: Optional[float] = None,
                        use_pallas: bool = True):
    """Flash-style attention via lax.scan over K/V blocks.  [B,H,T,D].

    On TPU, shapes whose K/V fit VMEM dispatch to the Pallas flash
    kernel (ops/pallas_attention.py): same online-softmax math, but the
    whole K-loop runs on-core with scores never touching HBM."""
    B, H, T, D = q.shape
    Tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if use_pallas:
        from ..ops import pallas_attention as pa
        if pa.flash_attention_available(B, H, T, Tk, D, q.dtype):
            flash = partial(pa.flash_attention, causal=causal, scale=scale,
                            block_q=block_size, block_k=block_size)
            if pa.INTERPRET:   # test hook: force the interpreter on CPU
                return flash(q, k, v)
            # platform resolved at LOWERING time: CPU-committed arrays on
            # a TPU host get the scan branch, never Mosaic (advisor r03);
            # jax versions without branch pruning resolve at trace time
            from ._compat import platform_dependent
            return platform_dependent(
                q, k, v, tpu=flash,
                default=partial(blockwise_attention, block_size=block_size,
                                causal=causal, scale=scale,
                                use_pallas=False))
    bs = min(block_size, Tk)
    nblocks = (Tk + bs - 1) // bs
    pad = nblocks * bs - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblocks, bs, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, bs, D).transpose(2, 0, 1, 3, 4)

    q_pos = jnp.arange(T)

    def body(carry, inp):
        m, l, o = carry
        kblk, vblk, blk_idx = inp
        k_pos = blk_idx * bs + jnp.arange(bs)
        bias = None
        mask_pad = k_pos < Tk
        bias = jnp.where(mask_pad, 0.0, -jnp.inf)[None, None, None, :]
        if causal:
            causal_mask = q_pos[:, None] >= k_pos[None, :]
            bias = bias + jnp.where(causal_mask, 0.0,
                                    -jnp.inf)[None, None, :, :]
        m, l, o = _attn_block(q, kblk, vblk, bias, m, l, o, scale)
        return (m, l, o), None

    # derive the carry from q so it inherits q's device-varying axes when
    # this runs inside shard_map (e.g. the Ulysses all-to-all path) — a
    # plain zeros() carry would mismatch the varying scan inputs
    zero = (q[..., 0] * 0).astype(jnp.float32)          # [B,H,T]
    m0 = zero - jnp.inf
    l0 = zero
    o0 = (q * 0).astype(jnp.float32)
    (m, l, o), _ = jax.lax.scan(body, (m0, l0, o0),
                                (kb, vb, jnp.arange(nblocks)))
    out = o / jnp.maximum(l[..., None], 1e-37)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, block_size: int = 512,
                   scale: Optional[float] = None, use_pallas: bool = True):
    """Exact attention with sequence sharded on `axis`.

    Inputs [B,H,T,D] with T = full sequence; returns same sharding.  Each
    of the n ring steps overlaps a K/V ``ppermute`` with attention over
    the already-held shard.  On TPU (lowering-time platform branch) the
    per-shard pass is the Pallas flash kernel emitting online-softmax
    stats (``flash_attention_stats``); the exact cross-shard combine
    (m/l rescaling) runs in XLA between steps, and for causal masks the
    per-step mask kind is resolved with ``lax.switch``: fully-visible
    shards run the kernel unmasked, the diagonal shard runs it causally,
    and fully-masked shards skip the kernel entirely (the classic ring
    load-saving).  The ring decomposition is also what makes the kernel
    APPLICABLE at long T: the VMEM gate sees the per-shard K/V (T/n),
    not the full sequence.  Backward (round 5) runs the Pallas dq/dk/dv
    kernels per shard against the forward's combined full-sequence
    (out, lse): dq accumulates locally while dk/dv accumulators ride the
    ring with their K/V shard — fused kernels in BOTH directions, like
    the reference's cuDNN ops (src/operator/cudnn_rnn-inl.h:1).
    """
    n = mesh.shape[axis]
    D = q.shape[-1]
    sc = scale if scale is not None else 1.0 / (D ** 0.5)

    def _pvary(*xs):
        # carries become device-varying after the first ppermute, so the
        # initial values must be marked varying over the ring axis too
        from ._compat import pvary
        return pvary(xs, (axis,))

    def per_shard_scan(qs, ks, vs):
        idx = jax.lax.axis_index(axis)
        T_loc = qs.shape[2]
        B, H = qs.shape[0], qs.shape[1]
        q_pos = idx * T_loc + jnp.arange(T_loc)

        def body(carry, step):
            m, l, o, kcur, vcur = carry
            src_block = (idx - step) % n
            k_pos = src_block * T_loc + jnp.arange(T_loc)
            bias = None
            if causal:
                bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0,
                                 -jnp.inf)[None, None, :, :]
            m, l, o = _attn_block(qs, kcur, vcur, bias, m, l, o, sc)
            perm = [(i, (i + 1) % n) for i in range(n)]
            knext = jax.lax.ppermute(kcur, axis, perm)
            vnext = jax.lax.ppermute(vcur, axis, perm)
            return (m, l, o, knext, vnext), None

        m0 = jnp.full((B, H, T_loc), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((B, H, T_loc), jnp.float32)
        o0 = jnp.zeros((B, H, T_loc, qs.shape[-1]), jnp.float32)
        m0, l0, o0 = _pvary(m0, l0, o0)
        (m, l, o, _, _), _ = jax.lax.scan(body, (m0, l0, o0, ks, vs),
                                          jnp.arange(n))
        out = o / jnp.maximum(l[..., None], 1e-37)
        return out.astype(qs.dtype)

    def per_shard_flash(qs, ks, vs):
        from ..ops import pallas_attention as pa
        idx = jax.lax.axis_index(axis)
        T_loc = qs.shape[2]
        B, H = qs.shape[0], qs.shape[1]
        bs = block_size

        def kernel_full(kc, vc):
            return pa.flash_attention_stats(qs, kc, vc, False, sc, bs, bs)

        def kernel_diag(kc, vc):
            return pa.flash_attention_stats(qs, kc, vc, True, sc, bs, bs)

        def kernel_skip(kc, vc):
            return (jnp.zeros((B, H, T_loc, qs.shape[-1]), jnp.float32),
                    jnp.full((B, H, T_loc), -jnp.inf, jnp.float32),
                    jnp.zeros((B, H, T_loc), jnp.float32))

        def body(carry, step):
            m, l, acc, kcur, vcur = carry
            if causal:
                src = (idx - step) % n
                # 0: src<idx fully visible; 1: diagonal (local causal);
                # 2: src>idx fully masked — kernel skipped
                mode = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
                acci, mi, li = jax.lax.switch(
                    mode, [kernel_full, kernel_diag, kernel_skip],
                    kcur, vcur)
            else:
                acci, mi, li = kernel_full(kcur, vcur)
            # exact online-softmax combine across shards
            m_new = jnp.maximum(m, mi)
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            a = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
            b = jnp.where(jnp.isfinite(mi), jnp.exp(mi - m_safe), 0.0)
            l_new = l * a + li * b
            acc_new = acc * a[..., None] + acci * b[..., None]
            perm = [(i, (i + 1) % n) for i in range(n)]
            knext = jax.lax.ppermute(kcur, axis, perm)
            vnext = jax.lax.ppermute(vcur, axis, perm)
            return (m_new, l_new, acc_new, knext, vnext), None

        m0 = jnp.full((B, H, T_loc), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((B, H, T_loc), jnp.float32)
        a0 = jnp.zeros((B, H, T_loc, qs.shape[-1]), jnp.float32)
        m0, l0, a0 = _pvary(m0, l0, a0)
        (m, l, acc, _, _), _ = jax.lax.scan(body, (m0, l0, a0, ks, vs),
                                            jnp.arange(n))
        out = acc / jnp.maximum(l[..., None], 1e-37)
        from ..ops import pallas_attention as pa
        return out.astype(qs.dtype), pa.lse_of(m, l)

    def per_shard_flash_bwd(qs, ks, vs, out, lse, g):
        """Ring backward with the Pallas dq/dk/dv kernels (round 5).

        The forward's combined (full-sequence) lse and out make each
        per-shard ``flash_attention_bwd`` call an exact partial: summing
        dq locally and carrying dk/dv accumulators around the ring WITH
        their K/V shard yields the exact gradients after n steps (each
        accumulator visits every Q shard once, then arrives home).
        """
        from ..ops import pallas_attention as pa
        idx = jax.lax.axis_index(axis)
        T_loc = qs.shape[2]
        B, H, D = qs.shape[0], qs.shape[1], qs.shape[-1]
        bs = block_size
        delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)

        def bwd_full(kc, vc):
            return pa.flash_attention_bwd(qs, kc, vc, g, lse, delta,
                                          False, sc, bs, bs)

        def bwd_diag(kc, vc):
            return pa.flash_attention_bwd(qs, kc, vc, g, lse, delta,
                                          True, sc, bs, bs)

        def bwd_skip(kc, vc):
            z = jnp.zeros((B, H, T_loc, D), jnp.float32)
            return z, z, z

        def body(carry, step):
            dq, kcur, vcur, dka, dva = carry
            if causal:
                src = (idx - step) % n
                mode = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
                dqi, dki, dvi = jax.lax.switch(
                    mode, [bwd_full, bwd_diag, bwd_skip], kcur, vcur)
            else:
                dqi, dki, dvi = bwd_full(kcur, vcur)
            dq = dq + dqi
            dka = dka + dki
            dva = dva + dvi
            perm = [(i, (i + 1) % n) for i in range(n)]
            knext = jax.lax.ppermute(kcur, axis, perm)
            vnext = jax.lax.ppermute(vcur, axis, perm)
            dka = jax.lax.ppermute(dka, axis, perm)
            dva = jax.lax.ppermute(dva, axis, perm)
            return (dq, knext, vnext, dka, dva), None

        z = jnp.zeros((B, H, T_loc, D), jnp.float32)
        dq0, dka0, dva0 = _pvary(z, z, z)
        (dq, _, _, dka, dva), _ = jax.lax.scan(
            body, (dq0, ks, vs, dka0, dva0), jnp.arange(n))
        return (dq.astype(qs.dtype), dka.astype(ks.dtype),
                dva.astype(vs.dtype))

    @jax.custom_vjp
    def _ring_flash(qs, ks, vs):
        out, _ = per_shard_flash(qs, ks, vs)
        return out

    def _rf_fwd(qs, ks, vs):
        out, lse = per_shard_flash(qs, ks, vs)
        return out, (qs, ks, vs, out, lse)

    def _rf_bwd(res, g):
        qs, ks, vs, out, lse = res
        return per_shard_flash_bwd(qs, ks, vs, out, lse, g)

    _ring_flash.defvjp(_rf_fwd, _rf_bwd)

    from ..ops import pallas_attention as pa
    B, H, T = q.shape[0], q.shape[1], q.shape[2]
    use_flash = use_pallas and T % n == 0 and \
        pa.flash_attention_available(B, H, T // n, T // n, D, q.dtype)

    def per_shard(qs, ks, vs):
        if pa.INTERPRET:        # test hook: force the interpreter on CPU
            return _ring_flash(qs, ks, vs)
        from ._compat import platform_dependent
        return platform_dependent(
            qs, ks, vs, tpu=_ring_flash, default=per_shard_scan)

    from ._compat import shard_map
    spec = P(None, None, axis, None)
    kw = {}
    if use_flash:
        # pallas_call inside shard_map is not vma-checkable (the per-shard
        # kernel's internal slices are unvarying); exactness vs the
        # checked scan formulation is pinned by tests.  Older jax spells
        # the flag check_rep — probe the signature instead of catching
        # TypeError, which would mask real errors.
        import inspect
        params = inspect.signature(shard_map).parameters
        flag = ("check_vma" if "check_vma" in params
                else "check_rep" if "check_rep" in params else None)
        if flag:
            kw = {flag: False}
    f = shard_map(per_shard if use_flash else per_shard_scan,
                  mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=spec, **kw)
    return f(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = False, scale: Optional[float] = None):
    """Ulysses/DeepSpeed-style: all-to-all so each chip gets ALL sequence for
    a subset of heads, runs full attention locally, then all-to-alls back."""
    from ._compat import shard_map

    n = mesh.shape[axis]

    def per_shard(qs, ks, vs):
        # [B, H, T/n, D] -> all_to_all over heads -> [B, H/n, T, D]
        def a2a(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)
        qh, kh, vh = a2a(qs), a2a(ks), a2a(vs)
        out = blockwise_attention(qh, kh, vh, causal=causal, scale=scale)
        return jax.lax.all_to_all(out, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    spec = P(None, None, axis, None)
    f = shard_map(per_shard, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=spec)
    return f(q, k, v)
