"""jax version-compat shims for the parallel/ package.

The manual-collective modules here track jax's SPMD API, which has moved
twice in supported releases:

* ``shard_map`` graduated from ``jax.experimental.shard_map`` to the
  top-level ``jax.shard_map``; older jaxlibs only ship the experimental
  spelling.  ``from jax import shard_map`` on those raises ImportError
  at call time and took out every tier-1 test that touches parallel/.
* the varying-axis cast is spelled ``jax.lax.pvary`` on current jax,
  ``jax.lax.pcast(..., to="varying")`` on the transitional releases,
  and does not exist at all before the check_vma typing landed — there
  the cast is a no-op because shard_map carries no varying-axis types
  (the matching ``check_rep`` flag is probed by callers off
  ``shard_map``'s signature, which keeps working through this shim
  since we re-export the real function, not a wrapper).
"""
from __future__ import annotations

import jax

try:                                    # jax >= 0.6: top-level export
    from jax import shard_map as shard_map  # type: ignore[attr-defined]
except ImportError:                     # older jax: experimental module
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map", "pvary", "platform_dependent"]


import functools


@functools.lru_cache(maxsize=None)
def _platform_dependent_prunes() -> bool:
    """True when ``jax.lax.platform_dependent`` statically prunes branches
    that don't match the lowering platform, so a Mosaic-only ``tpu``
    branch is harmless inside a CPU program.  Old jax lowers EVERY branch
    into the cond and the Pallas branch then fails CPU lowering outright.
    Probed behaviorally (one throwaway tiny compile, cached for the
    process): version sniffing would rot, and the failure mode of a wrong
    guess is a hard lowering error, not a silent wrong answer."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def _kernel(o_ref):
        o_ref[...] = jnp.zeros_like(o_ref)

    def _tpu():
        return pl.pallas_call(_kernel, out_shape=jax.ShapeDtypeStruct(
            (8, 128), jnp.float32))()

    def _default():
        return jnp.zeros((8, 128), jnp.float32)

    try:
        with jax.default_device(jax.devices("cpu")[0]):
            jax.jit(lambda: jax.lax.platform_dependent(
                tpu=_tpu, default=_default)).lower().compile()
        return True
    except Exception:  # noqa: BLE001 — any lowering failure means "no"
        return False


def platform_dependent(*args, default=None, **platform_branches):
    """``jax.lax.platform_dependent`` with a fallback for jax versions
    that can't carry un-lowerable branches: there the branch is resolved
    at TRACE time from the default backend instead of at lowering time.
    The trace-time fallback loses one nicety — CPU-committed arrays on a
    TPU host pick the tpu branch — which only the pruning versions can
    express at all."""
    if _platform_dependent_prunes():
        return jax.lax.platform_dependent(*args, default=default,
                                          **platform_branches)
    fn = platform_branches.get(jax.default_backend(), default)
    if fn is None:
        raise ValueError(
            "platform_dependent: no branch for backend %r and no default"
            % jax.default_backend())
    return fn(*args)


def pvary(xs, axis_names):
    """Mark ``xs`` device-varying over ``axis_names`` where the jax
    version has varying-axis types; identity where it doesn't (those
    versions never check, so an unmarked carry is already legal)."""
    axes = tuple(axis_names)
    lax = jax.lax
    if hasattr(lax, "pvary"):
        return lax.pvary(xs, axes)
    if hasattr(lax, "pcast"):
        return lax.pcast(xs, axes, to="varying")
    return xs
