"""Tensor (model) parallelism helpers.

Reference analog: none — the reference only has coarse layer-placement model
parallelism via ``ctx_group``/``group2ctx`` (SURVEY.md §2.2).  TPU-native TP
is pure sharding: annotate weight PartitionSpecs (megatron column/row splits)
and let pjit insert the all-reduces.  These helpers give the explicit
shard_map formulation for cases where manual collectives beat pjit's choices.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["column_parallel_dense", "row_parallel_dense", "mlp_block"]


def column_parallel_dense(x, w, mesh: Mesh, axis: str = "tp"):
    """y_local = x @ w_local  where w is [in, out/n] on each chip.
    No collective needed; output stays sharded on features."""
    from ._compat import shard_map
    f = shard_map(lambda xs, ws: jnp.dot(xs, ws), mesh=mesh,
                  in_specs=(P(), P(None, axis)), out_specs=P(None, axis))
    return f(x, w)


def row_parallel_dense(x, w, mesh: Mesh, axis: str = "tp"):
    """y = psum_i(x_local @ w_local) where x is feature-sharded and w is
    [in/n, out]: one all-reduce over ICI at the end (megatron row layer)."""
    from ._compat import shard_map

    def f(xs, ws):
        return jax.lax.psum(jnp.dot(xs, ws), axis)

    g = shard_map(f, mesh=mesh, in_specs=(P(None, axis), P(axis, None)),
                  out_specs=P())
    return g(x, w)


def mlp_block(x, w1, w2, mesh: Mesh, axis: str = "tp", act=jax.nn.relu):
    """Column-parallel up-proj + row-parallel down-proj: exactly one
    all-reduce per MLP block (the megatron pattern)."""
    h = column_parallel_dense(x, w1, mesh, axis)
    from ._compat import shard_map

    def down(hs, ws):
        return jax.lax.psum(jnp.dot(act(hs), ws), axis)

    g = shard_map(down, mesh=mesh, in_specs=(P(None, axis), P(axis, None)),
                  out_specs=P())
    return g(h, w2)
