"""Device mesh + sharding-rule helpers.

The scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
collectives.  Axis conventions: ``dp`` (data/batch), ``tp`` (tensor/model),
``sp`` (sequence/context), ``pp`` (pipeline stage), ``ep`` (expert).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_sharding", "replicated_sharding",
           "ShardingRules", "megatron_rules", "host_shard_hint", "P"]


def host_shard_hint(mesh: Optional[Mesh] = None,
                    axis: str = "dp") -> Tuple[int, int]:
    """(rank, nranks) hint for per-host sharded data loading.

    Each process of a multi-host mesh should decode only the slice of the
    global batch that lands on its local devices; feeding this tuple to
    ``io.NDArrayIter(num_parts=nranks, part_index=rank)`` (or any reader
    honoring the same contract) does exactly that.  On a single-host mesh
    this is (0, 1): the host decodes everything and ``jax.device_put``
    against the batch sharding splits it across local chips.
    """
    return int(jax.process_index()), int(jax.process_count())


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({'dp': 4, 'tp': 2}).

    Axis sizes must multiply to the device count; an axis size of -1 takes
    the remainder (like reshape).  Device order follows jax.devices(), which
    on TPU pods matches ICI adjacency for contiguous inner axes.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = list(axes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError("mesh %s does not fit %d devices" % (axes, n))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Batch-dim sharding for inputs."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


class ShardingRules:
    """Name-pattern → PartitionSpec rules for parameter pytrees.

    Megatron-style TP defaults: FC/conv weights split on the output-feature
    axis, paired projections split on input; biases and norms replicated.
    Users override per-pattern (regex on parameter name).
    """

    def __init__(self, mesh: Mesh, rules: Optional[Sequence] = None,
                 default: P = P()):
        import re
        self.mesh = mesh
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def spec_for(self, name: str, shape: Tuple[int, ...]) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                if self._fits(spec, shape):
                    return spec
        return self.default

    def sharding_for(self, name: str, shape) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec_for(name, tuple(shape)))

    def _fits(self, spec: P, shape) -> bool:
        if len(spec) > len(shape):
            return False
        for dim, ax in zip(shape, spec):
            if ax is None:
                continue
            size = self.mesh.shape[ax] if isinstance(ax, str) else \
                int(np.prod([self.mesh.shape[a] for a in ax]))
            if dim % size != 0:
                return False
        return True


def megatron_rules(mesh: Mesh, tp_axis: str = "tp") -> ShardingRules:
    """Default TP rules for our model zoo's parameter naming."""
    t = tp_axis
    return ShardingRules(mesh, rules=[
        # row-parallel (input-split) rule FIRST: out_proj/fc2/down names
        # also end in proj_weight/fc2_weight, which the column rule below
        # would otherwise claim — first match wins in spec_for
        (r"(out_proj|fc2|down)\w*_weight$", P(None, t)),
        (r"(fc|dense|proj|query|key|value)\d*_weight$", P(t, None)),
        (r"conv\w*_weight$", P(t, None, None, None)),
        (r"embedding\w*_weight$", P(None, t)),
    ])
