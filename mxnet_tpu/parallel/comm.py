"""Distributed communication backend: process group over jax.distributed.

Reference analog: ps-lite worker/server/scheduler roles over ZMQ
(SURVEY.md N12) + the dmlc_tracker launcher env (DMLC_ROLE, DMLC_PS_ROOT_URI).
TPU-native: a flat process group on the JAX distributed runtime — rank/size
from the coordinator, collectives as XLA ops over DCN/ICI.  The reference's
launcher env vars are honored so ``tools/launch.py``-style scripts keep
working: DMLC_NUM_WORKER → num processes, DMLC_WORKER_ID → rank,
DMLC_PS_ROOT_URI/PORT → coordinator address.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from ..base import get_env

__all__ = ["ProcessGroup", "process_group", "init_distributed"]

_initialized = False


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None):
    """Initialize the multi-host runtime (idempotent).

    Maps the reference launcher env (DMLC_*) onto jax.distributed; also
    accepts native JAX env (JAX_COORDINATOR_ADDRESS etc.).
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None and os.environ.get("DMLC_PS_ROOT_URI"):
        coordinator = "%s:%s" % (os.environ["DMLC_PS_ROOT_URI"],
                                 os.environ.get("DMLC_PS_ROOT_PORT", "9000"))
    num_processes = num_processes or get_env("DMLC_NUM_WORKER", None, int)
    process_id = process_id if process_id is not None \
        else get_env("DMLC_WORKER_ID", None, int)
    if coordinator is not None and num_processes and num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


class ProcessGroup:
    """Flat all-reduce group across JAX processes."""

    def __init__(self):
        init_distributed()
        self.rank = jax.process_index()
        self.size = jax.process_count()
        self._mesh = None
        self._sum_fn = None

    def _proc_mesh(self):
        """Mesh with ONE representative device per process — the DCN
        collective group (multi-pod-slice axis of SURVEY.md §5.8)."""
        if self._mesh is None:
            from jax.sharding import Mesh
            rep = {}
            for d in jax.devices():
                rep.setdefault(d.process_index, d)
            devs = [rep[p] for p in sorted(rep)]
            self._mesh = Mesh(np.asarray(devs), ("proc",))
        return self._mesh

    def allreduce(self, arr):
        """Cross-process sum.  Single-process: identity (local reduce
        already happened).  Multi-process: each process contributes its
        value as one shard of a process-sharded global array; a jit sum
        over the shard axis is XLA's all-reduce over DCN — the TPU
        replacement for the ps-lite push/aggregate cycle."""
        if self.size == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ndarray.ndarray import NDArray
        data = arr._data if isinstance(arr, NDArray) else \
            jax.numpy.asarray(arr)
        mesh = self._proc_mesh()
        sharding = NamedSharding(mesh, P("proc"))
        my_dev = mesh.devices.ravel()[self.rank]
        local = jax.device_put(jax.numpy.asarray(data)[None], my_dev)
        garr = jax.make_array_from_single_device_arrays(
            (self.size,) + tuple(data.shape), sharding, [local])
        if self._sum_fn is None:
            # ONE jitted collective reused for every push — a fresh lambda
            # per call would miss the jit cache and retrace each time
            self._sum_fn = jax.jit(lambda x: x.sum(axis=0),
                                   out_shardings=NamedSharding(mesh, P()))
        out = self._sum_fn(garr)
        # fully replicated: take this process's shard and co-locate it with
        # the input (no host round-trip; no foreign device commitment)
        result = jax.device_put(out.addressable_data(0),
                                next(iter(data.devices())))
        return NDArray(result, arr._ctx) if isinstance(arr, NDArray) \
            else result

    def broadcast(self, arr, root=0):
        if self.size == 1:
            return arr
        # psum of (x if rank==root else 0) — one collective
        from ..ndarray.ndarray import NDArray
        data = arr._data if isinstance(arr, NDArray) else arr
        scaled = data if self.rank == root else data * 0
        out = self.allreduce(NDArray(scaled, getattr(arr, "_ctx", None))
                             if isinstance(arr, NDArray) else scaled)
        return out

    def barrier(self):
        if self.size == 1:
            return
        from ..ndarray import ndarray as _nd
        one = _nd.ones((1,))
        self.allreduce(one).wait_to_read()


_group: Optional[ProcessGroup] = None


def process_group() -> ProcessGroup:
    global _group
    if _group is None:
        _group = ProcessGroup()
    return _group
