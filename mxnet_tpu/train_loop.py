"""Overlapped train loop: keep a bounded window of dispatched steps in
flight so the host-side tail of step N (metric D2H, logging) overlaps the
device execution of steps N+1..N+depth.

TPU-native analog of the reference engine's async dependency scheduling
(engine/threaded_engine.cc): there, WaitToRead on the loss is what
serialized the python loop; here jax's async dispatch already returns
control immediately, but any hard D2H (``.asnumpy()``) in the loop body
re-serializes it.  ``OverlappedLoop`` defers those blocking tails by
``depth`` steps:

    loop = OverlappedLoop(depth=2)
    for batch in train_iter:
        loss = trainer.step(batch)          # async dispatch
        loop.push(lambda l=loss: float(l.asnumpy()))   # blocks step N-2
    loop.drain()                            # settle the window

``depth=0`` degenerates to the fully serial dispatch->block loop (what
bench.py's blocked phase used to measure).  Default depth comes from
``MXNET_IO_OVERLAP_DEPTH``.
"""
from __future__ import annotations

import os
from collections import deque
from typing import Callable, Iterable, Optional

__all__ = ["OverlappedLoop", "default_overlap_depth", "run_epoch"]


def default_overlap_depth() -> int:
    """Window size for overlapped loops (``MXNET_IO_OVERLAP_DEPTH``, 2)."""
    try:
        return max(0, int(os.environ.get("MXNET_IO_OVERLAP_DEPTH", "2")))
    except ValueError:
        return 2


class OverlappedLoop:
    """Bounded FIFO of deferred per-step blockers.

    ``push(fn)`` enqueues the blocking tail of the step just dispatched;
    once more than ``depth`` tails are pending, the OLDEST one runs — so
    the host blocks on step N-depth while the device still has steps
    N-depth+1..N queued.  FIFO order means side effects (metric updates,
    callbacks) run in exact step order, just late.
    """

    def __init__(self, depth: Optional[int] = None):
        self.depth = default_overlap_depth() if depth is None else max(
            0, int(depth))
        self._pending: deque = deque()

    def __len__(self):
        return len(self._pending)

    def push(self, blocker: Callable[[], object]):
        """Defer `blocker`; run (and return the result of) the tail that
        falls out of the window, if any."""
        self._pending.append(blocker)
        out = None
        while len(self._pending) > self.depth:
            out = self._pending.popleft()()
        return out

    def drain(self):
        """Run every pending tail (epoch end); returns the last result."""
        out = None
        while self._pending:
            out = self._pending.popleft()()
        return out


def run_epoch(data_iter: Iterable, step_fn: Callable,
              block_fn: Optional[Callable] = None,
              depth: Optional[int] = None):
    """Drive one epoch with the dispatch/block phases overlapped.

    ``step_fn(batch)`` dispatches the (async) step and returns its
    handle; ``block_fn(handle, i)`` — optional — is the blocking tail,
    deferred ``depth`` steps behind dispatch.  Returns the number of
    batches consumed.
    """
    loop = OverlappedLoop(depth)
    n = 0
    for batch in data_iter:
        handle = step_fn(batch)
        if block_fn is not None:
            i = n
            loop.push(lambda h=handle, i=i: block_fn(h, i))
        n += 1
    loop.drain()
    return n
