"""2-bit gradient compression with error-feedback residual.

Reference analog: ``src/kvstore/gradient_compression.{h,cc,cu}`` (SURVEY.md
N13): ``kTwoBit`` stochastic-free threshold quantization — each gradient
element becomes {+threshold, 0, -threshold}; the quantization error is kept
in a per-key residual added to the next gradient (error feedback), so the
compressed stream is unbiased over time.  Wire format: 16 two-bit codes per
uint32 word (gradient_compression.cc quantize_2bit kernel).

TPU-native: the quantize/dequantize math is an XLA elementwise program; the
packed wire form is provided for DCN transport parity, while the in-process
dist path compresses semantically (quantize → all-reduce of dequantized
values), which is bit-equivalent to PS-side aggregation of decompressed
pushes.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["GradientCompression"]


class GradientCompression:
    """Threshold 2-bit compressor (reference gradient_compression.h:38-133)."""

    def __init__(self, type="2bit", threshold=0.5):  # noqa: A002 - ref name
        if type != "2bit":
            raise MXNetError("unsupported compression type %r "
                             "(reference supports kTwoBit only)" % type)
        if threshold <= 0:
            raise MXNetError("compression threshold must be > 0")
        self.type = type
        self.threshold = float(threshold)
        self._residuals: Dict[str, jax.Array] = {}

    def get_params(self):
        return {"type": self.type, "threshold": str(self.threshold)}

    # ---- semantic compression (the dist push path) -----------------------
    def compress(self, key: str, grad: jax.Array) -> jax.Array:
        """Quantize grad+residual to {-t, 0, +t}, updating the residual
        (error feedback — gradient_compression.cc quantize_2bit)."""
        t = self.threshold
        res = self._residuals.get(key)
        if res is None or res.shape != grad.shape:
            res = jnp.zeros_like(grad)
        acc = grad + res
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0)) \
            .astype(grad.dtype)
        self._residuals[key] = acc - q
        return q

    # ---- wire format (DCN transport parity) ------------------------------
    @staticmethod
    def pack(q: np.ndarray) -> np.ndarray:
        """Pack quantized values into 2-bit sign codes, 16 per uint32
        (codes: 0 = zero, 1 = positive, 2 = negative); magnitudes are
        implied by the threshold used at unpack."""
        flat = np.asarray(q, np.float32).ravel()
        codes = np.zeros(flat.shape, np.uint32)
        codes[flat > 0] = 1
        codes[flat < 0] = 2
        pad = (-len(codes)) % 16
        if pad:
            codes = np.concatenate([codes, np.zeros(pad, np.uint32)])
        codes = codes.reshape(-1, 16)
        words = np.zeros(codes.shape[0], np.uint32)
        for i in range(16):
            words |= codes[:, i] << np.uint32(2 * i)
        return words

    @staticmethod
    def unpack(words: np.ndarray, n: int, threshold: float,
               dtype=np.float32) -> np.ndarray:
        """Inverse of :meth:`pack`: first ``n`` codes back to values."""
        words = np.asarray(words, np.uint32)
        codes = np.zeros((len(words), 16), np.uint32)
        for i in range(16):
            codes[:, i] = (words >> np.uint32(2 * i)) & np.uint32(3)
        codes = codes.ravel()[:n]
        out = np.zeros(n, dtype)
        out[codes == 1] = threshold
        out[codes == 2] = -threshold
        return out
