"""Fused whole-step training dispatch.

The paper's "fast as the hardware allows" step has three launches on the
eager path: one fwdbwd XLA program plus a python loop of per-param
optimizer kernels plus per-param KVStore round-trips.  This module drives
the fused alternative: ``Executor.step_program`` compiles forward + vjp +
every optimizer update into ONE ``jax.jit`` with params and opt-state
donated (``donate_argnums``), so a local single-device step is exactly one
device launch and weights update in place.  Multi-device local training
keeps per-device fwdbwd programs and fuses the reduce+update phase into
one donated ``Executor.update_program`` per device.

Gated by ``MXNET_TPU_FUSED_STEP`` (default ON for the local path); the
eager per-param loop remains both the OFF fallback and the parity oracle —
any structural surprise (monitor installed, sparse grads, exotic optimizer
state, kvstore-side update) falls back per step, counted by
``step_dispatch_total{path=...}``.

Donation safety: XLA donation genuinely deletes the input buffer (also on
the CPU backend), while NDArray handles are freely re-pointed by python
callers (``set_params``, ``__setitem__``, ``set_states``).  ``DonationPool``
therefore tracks, per logical slot, the exact jax array the fused program
last produced; anything else found in the handle is defensively copied
before being donated, so no caller-held buffer is ever invalidated and no
donated buffer is ever double-used.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from . import atlas as _atlas
from . import telemetry as _telemetry
from . import health as _health
from . import memwatch as _memwatch

__all__ = ["enabled", "mesh_enabled", "ModuleFusedStep",
           "TrainerFusedUpdate", "TrainerMeshUpdate", "DonationPool",
           "STEP_DISPATCH", "STEP_TIME", "ENV_FLAG", "MESH_ENV_FLAG"]

ENV_FLAG = "MXNET_TPU_FUSED_STEP"
MESH_ENV_FLAG = "MXNET_TPU_MESH_STEP"

STEP_DISPATCH = _telemetry.counter(
    "step_dispatch_total",
    "Training-step dispatches by path: fused one-program step vs eager "
    "per-param loop; bucketed vs per-key KVStore gradient traffic",
    ("path",))
STEP_TIME = _telemetry.histogram(
    "step_update_seconds",
    "Wall time of the train-step update phase (fused path: the whole "
    "fwd+bwd+update program; eager path: the per-param update loop)")


def enabled():
    """MXNET_TPU_FUSED_STEP gate; default ON."""
    return os.environ.get(ENV_FLAG, "1").lower() not in \
        ("0", "false", "off", "")


def mesh_enabled():
    """MXNET_TPU_MESH_STEP gate; default ON.  Selects the GSPMD mesh
    variant of the fused step for local multi-device training: ONE global
    program over a device ``Mesh`` (XLA inserts the gradient all-reduce
    from the ``P('dp')`` batch sharding) instead of per-device programs
    plus a host-side KVStore reduce."""
    return os.environ.get(MESH_ENV_FLAG, "1").lower() not in \
        ("0", "false", "off", "")


def _env_tuple():
    from .executor import Executor
    return tuple(os.environ.get(k) for k in Executor.STEP_ENV_KEYS)


def _env_dict():
    """_env_tuple as {key: value} — the health/flight-dump snapshot form."""
    from .executor import Executor
    return {k: os.environ.get(k) for k in Executor.STEP_ENV_KEYS}


class DonationPool:
    """Ownership ledger for buffers the fused step donates.

    ``take`` returns a buffer safe to donate for a slot: the handle's
    current array if this pool produced it (nobody else can hold it — the
    program output went straight into the handle), else a fresh copy
    (externally written handles may share their buffer with caller-held
    arrays via no-op device_put/astype/broadcast_to).  ``give`` writes a
    program output back into the handle and records it as pool-owned.
    """

    def __init__(self):
        self._own = {}

    def take(self, slot, handle):
        cur = handle._data
        if self._own.get(slot) is not cur:
            cur = jnp.array(cur)
        return cur

    def take_sharded(self, slot, handle, sharding):
        """Donation-safe buffer for a mesh slot: the handle's array when
        pool-owned AND already laid out as ``sharding``; otherwise a
        genuine copy placed onto the mesh.  The copy must be
        ``jnp.array`` — ``jax.device_put`` may alias its input (even with
        ``may_alias=False`` on CPU), and donating an alias would delete
        the caller-held source buffer."""
        cur = handle._data
        if self._own.get(slot) is cur and \
                getattr(cur, "sharding", None) == sharding:
            return cur
        return jax.device_put(jnp.array(cur), sharding)

    def give(self, slot, handle, new_data):
        self._own[slot] = new_data
        handle._data = new_data
        if _memwatch.enabled:
            # Module-path slots are ("w", name)/("s", slot, j); Trainer
            # pools only ever hold donated opt-state (int-tuple slots).
            _memwatch.tag("params" if slot and slot[0] == "w"
                          else "opt_state", new_data)

    def disown(self, slot):
        """Forget a slot (its buffer escaped to non-pool code — e.g. the
        mesh global was re-placed per device): the next take copies."""
        self._own.pop(slot, None)


def _dense(arr):
    from .ndarray.sparse import BaseSparseNDArray
    return arr is not None and not isinstance(arr, BaseSparseNDArray)


def _copy_state_to(st, ctx):
    """Genuine per-device copy of an optimizer state pytree (None / NDArray
    / nested tuples-lists), used when de-meshing splits the single mesh
    state back into the per-device eager layout."""
    if st is None:
        return None
    if isinstance(st, (list, tuple)):
        return type(st)(_copy_state_to(s, ctx) for s in st)
    if hasattr(st, "copyto"):
        return st.copyto(ctx)
    return st


def _as_jax(arr):
    """Device array of an NDArray/array-like without a host bounce when
    the value is already device-resident."""
    data = getattr(arr, "_data", None)
    if data is not None:
        return data
    import numpy as _np
    return jnp.asarray(_np.asarray(arr))


class _StagedBatch:
    """A staged (deferred) train batch, materialised lazily in whichever
    layout the consumer needs: ``feeds()`` gives the per-device sliced
    feeds for the eager replay / per-device programs, ``full()`` the
    full-batch device arrays the mesh program shards on the ``dp`` axis —
    the mesh path never pays the per-device slice + placement work."""

    def __init__(self, eg, data_batch):
        self._eg = eg
        self._batch = data_batch
        self._feeds = None

    def feeds(self):
        if self._feeds is None:
            self._feeds = self._eg._load_batch(self._batch)
        return self._feeds

    def full(self):
        out = {}
        eg = self._eg
        for name, arr in zip(eg.data_names, self._batch.data):
            out[name] = _as_jax(arr)
        for name, arr in zip(eg.label_names, self._batch.label or []):
            out[name] = _as_jax(arr)
        return out


class ModuleFusedStep:
    """Drives Module's fused train step.

    ``forward_backward`` stages the per-device feeds; ``update`` then
    dispatches, for a single device, ONE whole-step program (fwd + vjp +
    update, params/opt-state donated) or, for multiple devices, the
    per-device fwdbwd programs followed by one donated update program per
    device.  Gradients are not written back to ``grad_dict`` on the
    single-device fused path (they only exist inside the program); the
    flush hooks replay a staged batch through the eager oracle whenever
    outputs or input grads must be observable before ``update``.
    """

    def __init__(self, module):
        self._mod = module
        self._eg = module._exec_group
        self._pools = [DonationPool() for _ in self._eg.execs]
        self._pending = None
        self._unsupported = False
        self._structural_ok = {}     # env tuple -> bool
        self._mesh_cache = None      # (key, (mesh, rules, dp_axis)|None)
        self._meshed = False         # handles currently hold mesh globals
        self._mesh_outputs = None    # full-batch outputs of the last step
        # program closures capture the optimizer binding; a new driver
        # (new init_optimizer / rebind) must not reuse a predecessor's
        for ex in self._eg.execs:
            for k in [k for k in ex._jitted
                      if isinstance(k, tuple) and k
                      and k[0] in ("step", "update")]:
                del ex._jitted[k]
        req = self._eg.grad_req
        self._pnames = [n for n in module._param_names
                        if req.get(n) == "write"]
        self._pset = set(self._pnames)
        self._has_add = any(req.get(n) == "add"
                            for n in module._param_names)

    # -- lifecycle --------------------------------------------------------
    def stale(self):
        return self._eg is not self._mod._exec_group

    @property
    def pending(self):
        return self._pending is not None

    def stage(self, data_batch):
        self._pending = _StagedBatch(self._eg, data_batch)
        self._mesh_outputs = None

    def flush_eager(self):
        """Replay a staged batch through the eager fwdbwd programs so
        outputs/grads/aux become observable exactly as if the batch had
        never been deferred.  Always de-meshes first: the per-device
        programs cannot consume 8-device globals.  Mesh outputs are
        invalidated unconditionally — the caller is about to run eager
        programs (e.g. ``score``'s eval forward), after which the last
        mesh step's outputs would be served stale by ``get_outputs`` /
        ``update_metric``."""
        self._mesh_outputs = None
        self._demesh()
        if self._pending is None:
            return
        staged, self._pending = self._pending, None
        for ex, feed in zip(self._eg.execs, staged.feeds()):
            ex.forward_backward(**feed)

    def mesh_outputs(self):
        """Full-batch outputs of the last mesh step, or None when a newer
        batch is pending / the last step was not mesh-dispatched."""
        return None if self.pending else self._mesh_outputs

    def demesh(self):
        """Public hook (Module.get_params / set_mesh): restore per-device
        handle layout without touching a staged batch."""
        self._demesh()

    # -- eligibility ------------------------------------------------------
    def eligible(self):
        if not enabled() or self._unsupported:
            return False
        m = self._mod
        if m._updater is None:  # update_on_kvstore
            return False
        kv = m._kvstore
        if kv is not None and (kv.type.startswith("dist")
                               or kv._updater is not None
                               or kv._compression is not None):
            return False
        for ex in self._eg.execs:
            if ex._monitor is not None or ex._group2ctx:
                return False
        # keyed by the step env values (bound dtypes are fixed, but the
        # dtype gate in supports_fused depends on optimizer mp config and
        # a stale cached verdict must not survive an env flip)
        env = _env_tuple()
        ok = self._structural_ok.get(env)
        if ok is None:
            ok = self._structural_ok[env] = self._check_structure()
        return ok

    def _check_structure(self):
        m = self._mod
        if self._eg.inputs_need_grad or self._has_add or not self._pnames:
            return False
        opt_ = m._optimizer
        if opt_.fused_state_arity() is None:
            return False
        for ex in self._eg.execs:
            for n in self._pnames:
                w = ex.arg_dict[n]
                if not _dense(w) or not _dense(ex.grad_dict.get(n)) \
                        or not opt_.supports_fused(w):
                    return False
        return True

    # -- dispatch ---------------------------------------------------------
    def step(self):
        """Consume the staged batch with fused programs.  Returns the
        dispatch path taken ("fused" / "mesh_fused", both truthy) or False
        (after replaying the batch eagerly) when the updater state turns
        out not to be fusable, so Module.update can run the eager loop."""
        m = self._mod
        opt_ = m._optimizer
        ndev = len(self._eg.execs)
        arity = opt_.fused_state_arity()
        # validate any pre-existing (e.g. preloaded) updater states before
        # touching counts or consuming the pending feed.  Expected layout
        # is per-slot: a low-precision weight's state carries the
        # master-fp32 leaf on top of the optimizer's own arity.
        from . import optimizer as _opt
        states = m._updater.states
        for slot, st in states.items():
            i, k = divmod(slot, ndev)
            if not (0 <= i < len(m._param_names) and k < ndev):
                self._unsupported = True
                self.flush_eager()
                return False
            w = self._eg.execs[k].arg_dict.get(m._param_names[i])
            mp = w is not None and opt_.fused_mp(w)
            leaves = _opt.fused_state_leaves(st, mp)
            if leaves is None or len(leaves) != arity + (1 if mp else 0):
                self._unsupported = True
                self.flush_eager()
                return False
        if ndev == 1:
            self._step_single()
            return "fused"
        if self._mesh_ok():
            return self._step_mesh()
        self._demesh()
        staged, self._pending = self._pending, None
        if staged is not None:
            for ex, feed in zip(self._eg.execs, staged.feeds()):
                ex.forward_backward(**feed)
        self._update_multi()
        return "fused"

    def _slots_for_device(self, ex, k, ndev):
        """Create-missing-state + count + capture per-slot scalars, in the
        exact order of the eager loop (param-major, device-minor ordering
        is handled by the caller for ndev > 1)."""
        out = []
        for i, name in enumerate(self._mod._param_names):
            if name in self._pset:
                out.extend(self._slots_for_device_one(ex, i, k, ndev))
        return out

    def _slot_mp(self, ex, name):
        """Whether this param's slot is multi-precision (bf16/f16 weight
        with a master-fp32 leaf prepended to its flat state)."""
        return self._mod._optimizer.fused_mp(ex.arg_dict[name])

    def _slot_leaves(self, ex, name, state):
        from . import optimizer as _opt
        return _opt.fused_state_leaves(state, self._slot_mp(ex, name))

    def _update_fns(self, ex, slots):
        """Per-slot traced update: the mp wrapper for low-precision
        weights, the plain fused core for fp32 ones — mixed layouts
        (bf16 conv weights + fp32 batchnorm scales) fuse into one
        program."""
        opt_ = self._mod._optimizer
        return [opt_.fused_update_mp if self._slot_mp(ex, s[0])
                else opt_.fused_update for s in slots]

    def _gather_update_inputs(self, ex, k, slots):
        """Pool-guarded param/state buffers + per-slot scalar arrays."""
        m = self._mod
        pool = self._pools[k]
        states = m._updater.states
        pvals, svals = [], []
        for name, slot, _, _, _ in slots:
            pvals.append(pool.take(("w", name), ex.arg_dict[name]))
            leaves = self._slot_leaves(ex, name, states[slot])
            svals.append(tuple(pool.take(("s", slot, j), leaf)
                               for j, leaf in enumerate(leaves)))
        lrs = jnp.asarray([s[2] for s in slots], jnp.float32)
        wds = jnp.asarray([s[3] for s in slots], jnp.float32)
        ts = jnp.asarray([s[4] for s in slots], jnp.float32)
        return pvals, svals, lrs, wds, ts

    def _writeback(self, ex, k, slots, new_p, new_s):
        pool = self._pools[k]
        states = self._mod._updater.states
        for (name, slot, _, _, _), w, st in zip(slots, new_p, new_s):
            pool.give(("w", name), ex.arg_dict[name], w)
            leaves = self._slot_leaves(ex, name, states[slot])
            for j, (leaf, arr) in enumerate(zip(leaves, st)):
                pool.give(("s", slot, j), leaf, arr)

    def _step_single(self):
        from . import profiler as _profiler
        from .ndarray.ndarray import NDArray
        m = self._mod
        opt_ = m._optimizer
        ex = self._eg.execs[0]
        staged, self._pending = self._pending, None
        feeds = staged.feeds() if staged is not None else None
        for kname, v in (feeds[0] if feeds else {}).items():
            dst = ex.arg_dict[kname]
            if isinstance(v, NDArray):
                # adopt pre-placed producer batches as-is (PrefetchingIter
                # device double buffering): no re-put, no same-dtype astype
                src = v._data
                dst._data = src if src.dtype == dst.dtype \
                    else src.astype(dst.dtype)
            else:
                dst._data = jnp.asarray(v, dst.dtype)
        slots = self._slots_for_device(ex, 0, 1)
        pvals, svals, lrs, wds, ts = self._gather_update_inputs(ex, 0, slots)
        rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
        others = [ex.arg_dict[n]._data for n in ex.arg_names
                  if n not in self._pset]
        auxs = [ex.aux_dict[n]._data for n in ex.aux_names]
        plan = ex._plan(True)
        keys = ex._keys(plan)
        ex._last_keys = keys
        ogs = ex._default_ograds()
        update_fns = self._update_fns(ex, slots)
        first_run = ex._step_key() not in ex._jitted
        fn = ex.step_program([s[0] for s in slots], update_fns)
        if first_run and _health.enabled:
            # lowering-only analysis — the dispatch below still owns the
            # one and only compilation of this program
            _health.register_program(
                "step", fn, (pvals, svals, others, auxs, keys, ogs, lrs,
                             wds, ts, rescale), donated=True,
                env=ex._program_env(plan))
        with _profiler.span("Executor::FusedStep", "executor",
                            args={"first_run": first_run}):
            new_p, new_s, outs, new_aux = fn(
                pvals, svals, others, auxs, keys, ogs, lrs, wds, ts, rescale)
        if first_run and _health.enabled:
            _health.audit_donation("step", (pvals, svals))
        self._writeback(ex, 0, slots, new_p, new_s)
        ex._writeback_aux(new_aux)
        ex._wrap_outputs(outs)

    def _update_multi(self):
        from . import profiler as _profiler
        m = self._mod
        opt_ = m._optimizer
        execs = self._eg.execs
        ndev = len(execs)
        reduce_grads = m._kvstore is not None
        # eager count order is param-major, device-minor: interleave the
        # per-device slot capture accordingly
        per_dev = [[] for _ in range(ndev)]
        for i, name in enumerate(m._param_names):
            if name not in self._pset:
                continue
            for k, ex in enumerate(execs):
                per_dev[k].extend(self._slots_for_device_one(ex, i, k, ndev))
        for k, ex in enumerate(execs):
            slots = per_dev[k]
            pvals, svals, lrs, wds, ts = \
                self._gather_update_inputs(ex, k, slots)
            dev = ex._ctx.jax_device
            gvals = []
            for name, _, _, _, _ in slots:
                if reduce_grads:
                    gvals.append([jax.device_put(e.grad_dict[name]._data, dev)
                                  for e in execs])
                else:
                    gvals.append([ex.grad_dict[name]._data])
            rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
            first_run = ex._update_key() not in ex._jitted
            fn = ex.update_program(self._update_fns(ex, slots))
            if first_run and k == 0 and _health.enabled:
                _health.register_program(
                    "update", fn, (pvals, svals, gvals, lrs, wds, ts,
                                   rescale), donated=True,
                    env=ex._program_env())
            with _profiler.span("Executor::FusedUpdate", "executor"):
                new_p, new_s = fn(pvals, svals, gvals, lrs, wds, ts, rescale)
            if first_run and k == 0 and _health.enabled:
                _health.audit_donation("update", (pvals, svals))
            self._writeback(ex, k, slots, new_p, new_s)

    def _slots_for_device_one(self, ex, i, k, ndev):
        """Single-param slot capture (multi-device interleaving order)."""
        m = self._mod
        opt_ = m._optimizer
        states = m._updater.states
        name = m._param_names[i]
        slot = opt_.slot_index(i, ndev, k)
        w = ex.arg_dict[name]
        if slot not in states:
            states[slot] = opt_.create_state_multi_precision(slot, w)
            m._updater.states_synced[slot] = True
        opt_._update_count(slot)
        t = opt_._index_update_count[slot]
        # host-side lr corrections (Adam's f64 bias fold) ride in the
        # captured lr so the traced program matches the eager oracle
        return [(name, slot, opt_.fused_slot_lr(opt_._get_lr(slot), t),
                 opt_._get_wd(slot), t)]

    # -- mesh (GSPMD) path ------------------------------------------------
    def on_mesh_change(self):
        """Module.set_mesh hook: drop the cached mesh so the next step
        re-derives shardings (and a new step-program cache key)."""
        self._demesh()
        self._mesh_cache = None

    def _mesh_setup(self):
        """(mesh, rules, dp_axis) over the module's contexts, or None when
        the context set cannot host one (duplicate devices, no dp axis,
        axis sizes not matching the device count)."""
        from .parallel.mesh import make_mesh
        m = self._mod
        axes = getattr(m, "_mesh_axes", None) or \
            {"dp": len(self._eg.execs)}
        rules = getattr(m, "_sharding_rules", None)
        key = (tuple(axes.items()), id(rules))
        if self._mesh_cache is not None and self._mesh_cache[0] == key:
            return self._mesh_cache[1]
        setup = None
        if "dp" in axes:
            devices = [c.jax_device for c in self._eg.contexts]
            if len({d.id for d in devices}) == len(devices):
                try:
                    mesh = make_mesh(dict(axes), devices=devices)
                    setup = (mesh, rules, "dp")
                except (ValueError, TypeError):
                    setup = None
        self._mesh_cache = (key, setup)
        return setup

    def _mesh_ok(self):
        """Mesh-path eligibility on top of ``eligible()``: local synced-DP
        semantics (a local kvstore selected), a buildable mesh, and a
        batch that shards evenly on axis 0 of every input."""
        if not mesh_enabled():
            return False
        eg = self._eg
        if len(eg.execs) <= 1 or self._mod._kvstore is None:
            return False
        if len({s.stop - s.start for s in eg.slices}) != 1:
            return False
        setup = self._mesh_setup()
        if setup is None:
            return False
        mesh, _, dp = setup
        bs = eg.batch_size
        if bs % mesh.shape[dp] != 0:
            return False
        from .io import DataDesc
        for d in list(eg.data_shapes) + list(eg.label_shapes or []):
            if d.shape[0] != bs or \
                    DataDesc.get_batch_axis(getattr(d, "layout", "NCHW")) != 0:
                return False
        return True

    def _slots_for_mesh(self, ex, ndev):
        """Per-param slot capture for the mesh step: ONE logical state per
        param, held in the device-0 slot of the eager layout; the sibling
        slots alias it so checkpoints (`get_states`) and the eager resume
        path keep seeing the layout they expect.  The count advances once
        per step — the global program IS the single update."""
        m = self._mod
        opt_ = m._optimizer
        states = m._updater.states
        out = []
        for i, name in enumerate(m._param_names):
            if name not in self._pset:
                continue
            base = opt_.slot_index(i, ndev, 0)
            w = ex.arg_dict[name]
            if base not in states:
                states[base] = opt_.create_state_multi_precision(base, w)
                m._updater.states_synced[base] = True
            opt_._update_count(base)
            cnt = opt_._index_update_count[base]
            for k in range(1, ndev):
                sib = opt_.slot_index(i, ndev, k)
                states[sib] = states[base]
                m._updater.states_synced[sib] = True
                opt_._index_update_count[sib] = cnt
            out.append((name, base,
                        opt_.fused_slot_lr(opt_._get_lr(base), cnt),
                        opt_._get_wd(base), cnt))
        return out

    def _take_mesh(self, slot, handles, sharding):
        """Pool-guarded donate-safe mesh placement of a set of handles that
        must agree (all execs' views of one param).  Divergent handles —
        some exec was written externally — disown the slot and copy."""
        pool = self._pools[0]
        cur = handles[0]._data
        if any(h._data is not cur for h in handles[1:]):
            pool.disown(slot)
        return pool.take_sharded(slot, handles[0], sharding)

    def _step_mesh(self):
        from . import optimizer as _opt
        from . import profiler as _profiler
        from .ndarray.ndarray import NDArray
        from jax.sharding import NamedSharding, PartitionSpec as P
        m = self._mod
        opt_ = m._optimizer
        eg = self._eg
        execs = eg.execs
        ex = execs[0]
        ndev = len(execs)
        mesh, rules, dp = self._mesh_setup()
        repl = NamedSharding(mesh, P())
        bsh = NamedSharding(mesh, P(dp))

        def psh(name, shape):
            if rules is not None:
                return rules.sharding_for(name, shape)
            return repl

        staged, self._pending = self._pending, None
        full = staged.full() if staged is not None else {}
        states = m._updater.states
        pool = self._pools[0]
        slots = self._slots_for_mesh(ex, ndev)
        pvals, svals = [], []
        for name, slot, _, _, _ in slots:
            sh = psh(name, ex.arg_dict[name].shape)
            pvals.append(self._take_mesh(
                ("w", name), [e.arg_dict[name] for e in execs], sh))
            # mp slots: leaf 0 is the master-fp32 copy — same shape as the
            # param, so it inherits the param's sharding like every moment
            leaves = self._slot_leaves(ex, name, states[slot])
            svals.append(tuple(
                pool.take_sharded(("s", slot, j), leaf, sh)
                for j, leaf in enumerate(leaves)))
        lrs = jnp.asarray([s[2] for s in slots], jnp.float32)
        wds = jnp.asarray([s[3] for s in slots], jnp.float32)
        ts = jnp.asarray([s[4] for s in slots], jnp.float32)
        rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
        batch_names = set(eg.data_names) | set(eg.label_names)
        others, full_shapes = [], {}
        for n in ex.arg_names:
            if n in self._pset:
                full_shapes[n] = ex.arg_dict[n].shape
                continue
            if n in batch_names:
                v = full.get(n)
                if v is None:       # replayed without a staged batch
                    v = ex.arg_dict[n]._data
                dt = ex.arg_dict[n].dtype
                if v.dtype != dt:
                    v = v.astype(dt)
                if getattr(v, "sharding", None) != bsh:
                    # producer-prefetched batches (PrefetchingIter with
                    # sharding=batch_sharding()) arrive pre-sharded: the
                    # H2D + shard already happened during the PREVIOUS step
                    v = jax.device_put(v, bsh)
                others.append(v)
                full_shapes[n] = tuple(v.shape)
            else:
                others.append(jax.device_put(ex.arg_dict[n]._data, repl))
                full_shapes[n] = ex.arg_dict[n].shape
        auxs = [jax.device_put(ex.aux_dict[n]._data, repl)
                for n in ex.aux_names]
        plan = ex._plan(True)
        keys = ex._keys(plan)
        ex._last_keys = keys
        ogs = ex._ograds_for(full_shapes)
        pshardings = [psh(s[0], ex.arg_dict[s[0]].shape) for s in slots]
        mesh_sig = (tuple(sorted(mesh.shape.items())),
                    tuple(str(sh.spec) for sh in pshardings))
        update_fns = self._update_fns(ex, slots)
        first_run = ex._step_key(mesh_sig) not in ex._jitted
        fn = ex.step_program([s[0] for s in slots], update_fns,
                             mesh_sig=mesh_sig, param_shardings=pshardings)
        if first_run and _health.enabled:
            _health.register_program(
                "mesh_step", fn, (pvals, svals, others, auxs, keys, ogs,
                                  lrs, wds, ts, rescale), donated=True,
                env=ex._program_env(plan))
        with _profiler.span("Mesh::Step", "executor",
                            args={"first_run": first_run,
                                  "mesh": str(dict(mesh.shape))}):
            new_p, new_s, outs, new_aux = fn(
                pvals, svals, others, auxs, keys, ogs, lrs, wds, ts, rescale)
        if first_run and _health.enabled:
            _health.audit_donation("mesh_step", (pvals, svals))
        for (name, slot, _, _, _), w, st in zip(slots, new_p, new_s):
            pool.give(("w", name), ex.arg_dict[name], w)
            for e in execs[1:]:
                e.arg_dict[name]._data = w
            leaves = self._slot_leaves(ex, name, states[slot])
            for j, (leaf, arr) in enumerate(zip(leaves, st)):
                pool.give(("s", slot, j), leaf, arr)
        for n, v in zip(ex.aux_names, new_aux):
            for e in execs:
                e.aux_dict[n]._data = v
        self._mesh_outputs = [NDArray(o, ex._ctx) for o in outs]
        self._meshed = True
        return "mesh_fused"

    def _demesh(self):
        """Point every exec's handles back at per-device arrays (the mesh
        globals are sliced/re-placed onto each context's device) and split
        the aliased mesh opt-state into genuine per-device copies, so the
        eager per-device programs and the local-kvstore reduce can resume
        seamlessly after any number of mesh steps."""
        if not self._meshed:
            return
        from . import optimizer as _opt
        m = self._mod
        execs = self._eg.execs
        ndev = len(execs)
        pool = self._pools[0]
        opt_ = m._optimizer
        states = m._updater.states if m._updater is not None else {}
        for i, name in enumerate(m._param_names):
            if name not in self._pset:
                continue
            g = execs[0].arg_dict[name]._data
            for e in execs:
                e.arg_dict[name]._data = jax.device_put(
                    g, e._ctx.jax_device)
            pool.disown(("w", name))
            base = opt_.slot_index(i, ndev, 0)
            st = states.get(base)
            if st is None:
                continue
            mp = opt_.fused_mp(execs[0].arg_dict[name])
            leaves = _opt.fused_state_leaves(st, mp) or []
            for j, leaf in enumerate(leaves):
                leaf._data = jax.device_put(
                    leaf._data, execs[0]._ctx.jax_device)
                pool.disown(("s", base, j))
            cnt = opt_._index_update_count.get(base)
            for k in range(1, ndev):
                sib = opt_.slot_index(i, ndev, k)
                states[sib] = _copy_state_to(st, execs[k]._ctx)
                m._updater.states_synced[sib] = True
                if cnt is not None:
                    opt_._index_update_count[sib] = cnt
        for n in self._eg.aux_names:
            g = execs[0].aux_dict[n]._data
            for e in execs:
                e.aux_dict[n]._data = jax.device_put(g, e._ctx.jax_device)
        self._meshed = False


class TrainerFusedUpdate:
    """Fused update phase for gluon.Trainer: one donated program per
    device replaces the per-param updater loop.  Weights are NOT donated
    (the autograd tape and user code may hold live references to
    ``param.data()`` buffers); optimizer state — which never escapes the
    updater un-copied — is."""

    def __init__(self, trainer):
        self._tr = trainer
        self._pools = [DonationPool() for _ in trainer._contexts]
        self._programs = {}
        self._unsupported = False

    def eligible(self):
        if not enabled() or self._unsupported:
            return False
        tr = self._tr
        if tr._update_on_kvstore:
            return False
        opt_ = tr._optimizer
        if opt_.fused_state_arity() is None:
            return False
        for p in tr._params:
            if p.grad_req == "null":
                continue
            if getattr(p, "_stype", "default") != "default" or \
                    getattr(p, "_grad_stype", "default") != "default":
                return False
            if not opt_.supports_fused(p.list_data()[0]):
                return False
        return True

    def step(self):
        from . import optimizer as _opt
        from . import profiler as _profiler
        tr = self._tr
        opt_ = tr._optimizer
        live = [(i, p) for i, p in enumerate(tr._params)
                if p.grad_req != "null"]
        if not live:
            return True
        arity = opt_.fused_state_arity()
        ncty = len(tr._contexts)
        per_dev = [{"p": [], "s": [], "g": [], "lr": [], "wd": [], "t": []}
                   for _ in range(ncty)]
        update_fns = []
        # eager order: param-major, device-minor — each device's updater
        # shares the optimizer, so the update count really does advance
        # once per (param, device) visit
        for i, p in live:
            datas, grads = p.list_data(), p.list_grad()
            mp = opt_.fused_mp(datas[0])
            update_fns.append(opt_.fused_update_mp if mp
                              else opt_.fused_update)
            for k, upd in enumerate(tr._updaters):
                w = datas[k]
                if i not in upd.states:
                    upd.states[i] = \
                        opt_.create_state_multi_precision(i, w)
                    upd.states_synced[i] = True
                leaves = _opt.fused_state_leaves(upd.states[i], mp)
                if leaves is None or len(leaves) != arity + (1 if mp else 0):
                    self._unsupported = True
                    return False
                opt_._update_count(i)
                d = per_dev[k]
                d["p"].append(w._data)
                d["s"].append(tuple(self._pools[k].take((i, j), leaf)
                                    for j, leaf in enumerate(leaves)))
                d["g"].append([grads[k]._data])
                d["lr"].append(opt_.fused_slot_lr(
                    opt_._get_lr(i), opt_._index_update_count[i]))
                d["wd"].append(opt_._get_wd(i))
                d["t"].append(opt_._index_update_count[i])
        rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
        env = _env_tuple()
        fn = self._programs.get(env)
        first_run = fn is None
        if fn is None:
            from .executor import build_update_program
            fn = build_update_program(update_fns, donate_params=False)
            self._programs[env] = fn
        if first_run and _health.enabled and per_dev:
            d0 = per_dev[0]
            _health.register_program(
                "trainer_update", fn,
                (d0["p"], d0["s"], d0["g"],
                 jnp.asarray(d0["lr"], jnp.float32),
                 jnp.asarray(d0["wd"], jnp.float32),
                 jnp.asarray(d0["t"], jnp.float32), rescale), donated=True,
                env=_env_dict())
        for k in range(ncty):
            d = per_dev[k]
            with _profiler.span("Trainer::FusedUpdate", "executor"):
                new_p, new_s = fn(
                    d["p"], d["s"], d["g"],
                    jnp.asarray(d["lr"], jnp.float32),
                    jnp.asarray(d["wd"], jnp.float32),
                    jnp.asarray(d["t"], jnp.float32), rescale)
            if first_run and k == 0 and _health.enabled:
                # only opt-state is donated here (donate_params=False)
                _health.audit_donation("trainer_update", d["s"])
            pool = self._pools[k]
            for (i, p), w, st in zip(live, new_p, new_s):
                p.list_data()[k]._data = w
                if _memwatch.enabled:
                    _memwatch.tag("params", w)
                leaves = _opt.fused_state_leaves(
                    tr._updaters[k].states[i], opt_.fused_mp(p.list_data()[k]))
                for j, (leaf, arr) in enumerate(zip(leaves, st)):
                    pool.give((i, j), leaf, arr)
        return True


def _adopt(shape, sharding, arrs):
    """Zero-copy global from per-device committed arrays (the sources stay
    alive; donating the adopted global deletes them)."""
    return jax.make_array_from_single_device_arrays(
        tuple(shape), sharding, list(arrs))


def build_mesh_update_program(update_fns, ndev, out_sharding):
    """Donated GSPMD update program for the Trainer mesh path.

    Inputs: replicated params/opt-state globals and per-device gradients
    adopted as ``P('dp')`` shards of a ``(ndev*d0, ...)`` global; the
    leading-axis reshape+sum below IS the gradient all-reduce — XLA lowers
    the reduction over the sharded axis to a collective over ICI.  Only
    opt-state (argument 1) is donated: weights and grads were adopted
    zero-copy from buffers the autograd tape / user code may still hold.
    ``out_sharding`` pins outputs replicated so every device holds a full
    shard for the per-device writeback.
    """
    update_fns = tuple(update_fns)

    def fn(pvals, svals, gvals, lrs, wds, ts, rescale):
        new_p, new_s = [], []
        for i, upd in enumerate(update_fns):
            with jax.named_scope(_atlas.GRAD_SYNC):
                g = gvals[i]
                g = g.reshape((ndev, g.shape[0] // ndev) + g.shape[1:]) \
                     .sum(0)
            with jax.named_scope(_atlas.optimizer_scope(upd)):
                w, s = upd(pvals[i], g, svals[i], lrs[i], wds[i], rescale,
                           ts[i])
            w = jax.lax.with_sharding_constraint(w, out_sharding)
            s = jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(a, out_sharding),
                s)
            new_p.append(w)
            new_s.append(s)
        return new_p, new_s

    return jax.jit(fn, donate_argnums=(1,))


class TrainerMeshUpdate:
    """Mesh-native reduce+update phase for gluon.Trainer on local
    multi-device: per-device weight replicas and raw (un-reduced) gradient
    buffers are adopted zero-copy into globals over a ``dp`` mesh, and ONE
    GSPMD program does the gradient all-reduce plus every optimizer update
    — replacing the host-side KVStore push/pull reduce and the per-device
    update programs entirely.

    Update-count semantics follow the single-device step (one logical
    update per param per step), unlike the eager multi-device loop whose
    shared optimizer advances the count once per (param, device) visit.
    """

    def __init__(self, trainer):
        self._tr = trainer
        self._pools = [DonationPool() for _ in trainer._contexts]
        self._programs = {}
        self._unsupported = False
        self._mesh = None          # None = unprobed, False = cannot build
        self._devids = [c.jax_device.id for c in trainer._contexts]

    def _mesh_setup(self):
        from .parallel.mesh import make_mesh
        if self._mesh is None:
            devices = [c.jax_device for c in self._tr._contexts]
            if len({d.id for d in devices}) != len(devices):
                self._mesh = False
            else:
                self._mesh = make_mesh({"dp": len(devices)},
                                       devices=devices)
        return self._mesh or None

    def eligible(self):
        if not enabled() or not mesh_enabled() or self._unsupported:
            return False
        tr = self._tr
        if len(tr._contexts) <= 1 or tr._update_on_kvstore:
            return False
        kv = tr._kvstore
        # a local kvstore signals synced-DP semantics (the reduce we fuse
        # in-program); no kvstore means intentionally unsynced replicas
        if kv is None or kv.type.startswith("dist") \
                or getattr(kv, "_updater", None) is not None \
                or getattr(kv, "_compression", None) is not None:
            return False
        opt_ = tr._optimizer
        if opt_.fused_state_arity() is None:
            return False
        for p in tr._params:
            if p.grad_req == "null":
                continue
            if getattr(p, "_stype", "default") != "default" or \
                    getattr(p, "_grad_stype", "default") != "default":
                return False
            w0 = p.list_data()[0]
            if not opt_.supports_fused(w0) or len(w0.shape) == 0:
                return False
        return self._mesh_setup() is not None

    def step(self):
        from . import optimizer as _opt
        from . import profiler as _profiler
        from jax.sharding import NamedSharding, PartitionSpec as P
        tr = self._tr
        opt_ = tr._optimizer
        mesh = self._mesh_setup()
        ndev = len(tr._contexts)
        live = [(i, p) for i, p in enumerate(tr._params)
                if p.grad_req != "null"]
        if not live:
            return True
        arity = opt_.fused_state_arity()
        repl = NamedSharding(mesh, P())
        gsh = NamedSharding(mesh, P("dp"))
        # validate/create every state BEFORE any adoption: a donation-bound
        # program must never launch with half-captured inputs
        mps = {i: opt_.fused_mp(p.list_data()[0]) for i, p in live}
        for i, p in live:
            nleaves = arity + (1 if mps[i] else 0)
            for k, upd in enumerate(tr._updaters):
                if i not in upd.states:
                    upd.states[i] = opt_.create_state_multi_precision(
                        i, p.list_data()[k])
                    upd.states_synced[i] = True
                leaves = _opt.fused_state_leaves(upd.states[i], mps[i])
                if leaves is None or len(leaves) != nleaves:
                    self._unsupported = True
                    return False
        pvals, svals, gvals, lrs, wds, ts = [], [], [], [], [], []
        try:
            for i, p in live:
                datas = [d._data for d in p.list_data()]
                grads = [g._data for g in p.list_grad()]
                pvals.append(_adopt(datas[0].shape, repl, datas))
                per_leaf = []
                for j in range(arity + (1 if mps[i] else 0)):
                    leaves_k = [_opt.fused_state_leaves(
                        tr._updaters[k].states[i], mps[i])[j]
                        for k in range(ndev)]
                    per_leaf.append(self._take_state((i, j), leaves_k, repl))
                svals.append(tuple(per_leaf))
                gshape = (ndev * grads[0].shape[0],) + grads[0].shape[1:]
                gvals.append(_adopt(gshape, gsh, grads))
        except (ValueError, TypeError):
            # adoption needs committed per-device buffers of equal shape;
            # anything else (uncommitted arrays, ragged replicas) falls
            # back to the per-device fused path for good
            self._unsupported = True
            return False
        for i, p in live:
            # one LOGICAL update per param per step: the global program IS
            # the single update (single-device count semantics)
            opt_._update_count(i)
            lrs.append(opt_.fused_slot_lr(
                opt_._get_lr(i), opt_._index_update_count[i]))
            wds.append(opt_._get_wd(i))
            ts.append(opt_._index_update_count[i])
        env = _env_tuple()
        key = (env, tuple(sorted(mesh.shape.items())), len(live))
        fn = self._programs.get(key)
        first_run = fn is None
        if fn is None:
            fn = build_mesh_update_program(
                [opt_.fused_update_mp if mps[i] else opt_.fused_update
                 for i, p in live], ndev, repl)
            self._programs[key] = fn
        if first_run and _health.enabled:
            _health.register_program(
                "trainer_mesh_update", fn,
                (pvals, svals, gvals,
                 jnp.asarray(lrs, jnp.float32), jnp.asarray(wds, jnp.float32),
                 jnp.asarray(ts, jnp.float32),
                 jnp.asarray(opt_.rescale_grad, jnp.float32)), donated=True,
                env=_env_dict())
        with _profiler.span("Mesh::Step", "executor",
                            args={"path": "trainer",
                                  "mesh": str(dict(mesh.shape))}):
            new_p, new_s = fn(
                pvals, svals, gvals,
                jnp.asarray(lrs, jnp.float32), jnp.asarray(wds, jnp.float32),
                jnp.asarray(ts, jnp.float32),
                jnp.asarray(opt_.rescale_grad, jnp.float32))
        if first_run and _health.enabled:
            # only opt-state is donated here (weights/grads were adopted
            # zero-copy from buffers user code may still hold)
            _health.audit_donation("trainer_mesh_update", svals)
        for (i, p), w, st in zip(live, new_p, new_s):
            self._scatter(p.list_data(), w)
            for j in range(arity + (1 if mps[i] else 0)):
                leaves_k = [_opt.fused_state_leaves(
                    tr._updaters[k].states[i], mps[i])[j]
                    for k in range(ndev)]
                self._scatter_state((i, j), leaves_k, st[j])
        return True

    def _take_state(self, slot, leaves_k, sharding):
        """Opt-state global for donation: zero-copy adoption of the
        per-device leaves when every pool owns its device's buffer, else a
        genuine copy of device-0's value (the writeback re-syncs all
        devices)."""
        datas = [leaf._data for leaf in leaves_k]
        if all(self._pools[k]._own.get(slot) is datas[k]
               for k in range(len(datas))):
            return _adopt(datas[0].shape, sharding, datas)
        return jax.device_put(jnp.array(datas[0]), sharding)

    def _scatter(self, handles, global_arr):
        """Write a replicated program output back as per-device arrays."""
        shards = {s.device.id: s.data for s in global_arr.addressable_shards}
        for k, h in enumerate(handles):
            h._data = shards[self._devids[k]]
        if _memwatch.enabled:
            _memwatch.tag("params", list(shards.values()))

    def _scatter_state(self, slot, leaves_k, global_arr):
        shards = {s.device.id: s.data for s in global_arr.addressable_shards}
        for k, leaf in enumerate(leaves_k):
            self._pools[k].give(slot, leaf, shards[self._devids[k]])
