"""Fused whole-step training dispatch.

The paper's "fast as the hardware allows" step has three launches on the
eager path: one fwdbwd XLA program plus a python loop of per-param
optimizer kernels plus per-param KVStore round-trips.  This module drives
the fused alternative: ``Executor.step_program`` compiles forward + vjp +
every optimizer update into ONE ``jax.jit`` with params and opt-state
donated (``donate_argnums``), so a local single-device step is exactly one
device launch and weights update in place.  Multi-device local training
keeps per-device fwdbwd programs and fuses the reduce+update phase into
one donated ``Executor.update_program`` per device.

Gated by ``MXNET_TPU_FUSED_STEP`` (default ON for the local path); the
eager per-param loop remains both the OFF fallback and the parity oracle —
any structural surprise (monitor installed, sparse grads, exotic optimizer
state, kvstore-side update) falls back per step, counted by
``step_dispatch_total{path=...}``.

Donation safety: XLA donation genuinely deletes the input buffer (also on
the CPU backend), while NDArray handles are freely re-pointed by python
callers (``set_params``, ``__setitem__``, ``set_states``).  ``DonationPool``
therefore tracks, per logical slot, the exact jax array the fused program
last produced; anything else found in the handle is defensively copied
before being donated, so no caller-held buffer is ever invalidated and no
donated buffer is ever double-used.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from . import telemetry as _telemetry

__all__ = ["enabled", "ModuleFusedStep", "TrainerFusedUpdate",
           "DonationPool", "STEP_DISPATCH", "STEP_TIME", "ENV_FLAG"]

ENV_FLAG = "MXNET_TPU_FUSED_STEP"

STEP_DISPATCH = _telemetry.counter(
    "step_dispatch_total",
    "Training-step dispatches by path: fused one-program step vs eager "
    "per-param loop; bucketed vs per-key KVStore gradient traffic",
    ("path",))
STEP_TIME = _telemetry.histogram(
    "step_update_seconds",
    "Wall time of the train-step update phase (fused path: the whole "
    "fwd+bwd+update program; eager path: the per-param update loop)")


def enabled():
    """MXNET_TPU_FUSED_STEP gate; default ON."""
    return os.environ.get(ENV_FLAG, "1").lower() not in \
        ("0", "false", "off", "")


def _env_tuple():
    from .executor import Executor
    return tuple(os.environ.get(k) for k in Executor.STEP_ENV_KEYS)


class DonationPool:
    """Ownership ledger for buffers the fused step donates.

    ``take`` returns a buffer safe to donate for a slot: the handle's
    current array if this pool produced it (nobody else can hold it — the
    program output went straight into the handle), else a fresh copy
    (externally written handles may share their buffer with caller-held
    arrays via no-op device_put/astype/broadcast_to).  ``give`` writes a
    program output back into the handle and records it as pool-owned.
    """

    def __init__(self):
        self._own = {}

    def take(self, slot, handle):
        cur = handle._data
        if self._own.get(slot) is not cur:
            cur = jnp.array(cur)
        return cur

    def give(self, slot, handle, new_data):
        self._own[slot] = new_data
        handle._data = new_data


def _dense(arr):
    from .ndarray.sparse import BaseSparseNDArray
    return arr is not None and not isinstance(arr, BaseSparseNDArray)


class ModuleFusedStep:
    """Drives Module's fused train step.

    ``forward_backward`` stages the per-device feeds; ``update`` then
    dispatches, for a single device, ONE whole-step program (fwd + vjp +
    update, params/opt-state donated) or, for multiple devices, the
    per-device fwdbwd programs followed by one donated update program per
    device.  Gradients are not written back to ``grad_dict`` on the
    single-device fused path (they only exist inside the program); the
    flush hooks replay a staged batch through the eager oracle whenever
    outputs or input grads must be observable before ``update``.
    """

    def __init__(self, module):
        self._mod = module
        self._eg = module._exec_group
        self._pools = [DonationPool() for _ in self._eg.execs]
        self._pending = None
        self._unsupported = False
        self._structural_ok = None
        # program closures capture the optimizer binding; a new driver
        # (new init_optimizer / rebind) must not reuse a predecessor's
        for ex in self._eg.execs:
            for k in [k for k in ex._jitted
                      if isinstance(k, tuple) and k
                      and k[0] in ("step", "update")]:
                del ex._jitted[k]
        req = self._eg.grad_req
        self._pnames = [n for n in module._param_names
                        if req.get(n) == "write"]
        self._pset = set(self._pnames)
        self._has_add = any(req.get(n) == "add"
                            for n in module._param_names)

    # -- lifecycle --------------------------------------------------------
    def stale(self):
        return self._eg is not self._mod._exec_group

    @property
    def pending(self):
        return self._pending is not None

    def stage(self, data_batch):
        self._pending = self._eg._load_batch(data_batch)

    def flush_eager(self):
        """Replay a staged batch through the eager fwdbwd programs so
        outputs/grads/aux become observable exactly as if the batch had
        never been deferred."""
        if self._pending is None:
            return
        feeds, self._pending = self._pending, None
        for ex, feed in zip(self._eg.execs, feeds):
            ex.forward_backward(**feed)

    # -- eligibility ------------------------------------------------------
    def eligible(self):
        if not enabled() or self._unsupported:
            return False
        m = self._mod
        if m._updater is None:  # update_on_kvstore
            return False
        kv = m._kvstore
        if kv is not None and (kv.type.startswith("dist")
                               or kv._updater is not None
                               or kv._compression is not None):
            return False
        for ex in self._eg.execs:
            if ex._monitor is not None or ex._group2ctx:
                return False
        if self._structural_ok is None:
            self._structural_ok = self._check_structure()
        return self._structural_ok

    def _check_structure(self):
        m = self._mod
        if self._eg.inputs_need_grad or self._has_add or not self._pnames:
            return False
        opt_ = m._optimizer
        if opt_.fused_state_arity() is None:
            return False
        for ex in self._eg.execs:
            for n in self._pnames:
                w = ex.arg_dict[n]
                if not _dense(w) or not _dense(ex.grad_dict.get(n)) \
                        or not opt_.supports_fused(w):
                    return False
        return True

    # -- dispatch ---------------------------------------------------------
    def step(self):
        """Consume the staged batch with fused programs.  Returns False
        (after replaying the batch eagerly) when the updater state turns
        out not to be fusable, so Module.update can run the eager loop."""
        m = self._mod
        opt_ = m._optimizer
        ndev = len(self._eg.execs)
        arity = opt_.fused_state_arity()
        # validate any pre-existing (e.g. preloaded) updater states before
        # touching counts or consuming the pending feed
        from . import optimizer as _opt
        states = m._updater.states
        for slot, st in states.items():
            leaves = _opt.fused_state_leaves(st)
            if leaves is None or len(leaves) != arity:
                self._unsupported = True
                self.flush_eager()
                return False
        if ndev == 1:
            self._step_single()
        else:
            feeds, self._pending = self._pending, None
            if feeds is not None:
                for ex, feed in zip(self._eg.execs, feeds):
                    ex.forward_backward(**feed)
            self._update_multi()
        return True

    def _slots_for_device(self, ex, k, ndev):
        """Create-missing-state + count + capture per-slot scalars, in the
        exact order of the eager loop (param-major, device-minor ordering
        is handled by the caller for ndev > 1)."""
        out = []
        for i, name in enumerate(self._mod._param_names):
            if name in self._pset:
                out.extend(self._slots_for_device_one(ex, i, k, ndev))
        return out

    def _gather_update_inputs(self, ex, k, slots):
        """Pool-guarded param/state buffers + per-slot scalar arrays."""
        from . import optimizer as _opt
        m = self._mod
        pool = self._pools[k]
        states = m._updater.states
        pvals, svals = [], []
        for name, slot, _, _, _ in slots:
            pvals.append(pool.take(("w", name), ex.arg_dict[name]))
            leaves = _opt.fused_state_leaves(states[slot])
            svals.append(tuple(pool.take(("s", slot, j), leaf)
                               for j, leaf in enumerate(leaves)))
        lrs = jnp.asarray([s[2] for s in slots], jnp.float32)
        wds = jnp.asarray([s[3] for s in slots], jnp.float32)
        ts = jnp.asarray([s[4] for s in slots], jnp.float32)
        return pvals, svals, lrs, wds, ts

    def _writeback(self, ex, k, slots, new_p, new_s):
        from . import optimizer as _opt
        pool = self._pools[k]
        states = self._mod._updater.states
        for (name, slot, _, _, _), w, st in zip(slots, new_p, new_s):
            pool.give(("w", name), ex.arg_dict[name], w)
            leaves = _opt.fused_state_leaves(states[slot])
            for j, (leaf, arr) in enumerate(zip(leaves, st)):
                pool.give(("s", slot, j), leaf, arr)

    def _step_single(self):
        from . import profiler as _profiler
        from .ndarray.ndarray import NDArray
        m = self._mod
        opt_ = m._optimizer
        ex = self._eg.execs[0]
        feeds, self._pending = self._pending, None
        for kname, v in (feeds[0] if feeds else {}).items():
            dst = ex.arg_dict[kname]
            dst._data = v._data.astype(dst.dtype) if isinstance(v, NDArray) \
                else jnp.asarray(v, dst.dtype)
        slots = self._slots_for_device(ex, 0, 1)
        pvals, svals, lrs, wds, ts = self._gather_update_inputs(ex, 0, slots)
        rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
        others = [ex.arg_dict[n]._data for n in ex.arg_names
                  if n not in self._pset]
        auxs = [ex.aux_dict[n]._data for n in ex.aux_names]
        plan = ex._plan(True)
        keys = ex._keys(plan)
        ex._last_keys = keys
        ogs = ex._default_ograds()
        update_fns = [opt_.fused_update] * len(slots)
        first_run = ("step",) + ex._step_env() not in ex._jitted
        fn = ex.step_program([s[0] for s in slots], update_fns)
        with _profiler.span("Executor::FusedStep", "executor",
                            args={"first_run": first_run}):
            new_p, new_s, outs, new_aux = fn(
                pvals, svals, others, auxs, keys, ogs, lrs, wds, ts, rescale)
        self._writeback(ex, 0, slots, new_p, new_s)
        ex._writeback_aux(new_aux)
        ex._wrap_outputs(outs)

    def _update_multi(self):
        from . import profiler as _profiler
        m = self._mod
        opt_ = m._optimizer
        execs = self._eg.execs
        ndev = len(execs)
        reduce_grads = m._kvstore is not None
        # eager count order is param-major, device-minor: interleave the
        # per-device slot capture accordingly
        per_dev = [[] for _ in range(ndev)]
        for i, name in enumerate(m._param_names):
            if name not in self._pset:
                continue
            for k, ex in enumerate(execs):
                per_dev[k].extend(self._slots_for_device_one(ex, i, k, ndev))
        for k, ex in enumerate(execs):
            slots = per_dev[k]
            pvals, svals, lrs, wds, ts = \
                self._gather_update_inputs(ex, k, slots)
            dev = ex._ctx.jax_device
            gvals = []
            for name, _, _, _, _ in slots:
                if reduce_grads:
                    gvals.append([jax.device_put(e.grad_dict[name]._data, dev)
                                  for e in execs])
                else:
                    gvals.append([ex.grad_dict[name]._data])
            rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
            fn = ex.update_program([opt_.fused_update] * len(slots))
            with _profiler.span("Executor::FusedUpdate", "executor"):
                new_p, new_s = fn(pvals, svals, gvals, lrs, wds, ts, rescale)
            self._writeback(ex, k, slots, new_p, new_s)

    def _slots_for_device_one(self, ex, i, k, ndev):
        """Single-param slot capture (multi-device interleaving order)."""
        m = self._mod
        opt_ = m._optimizer
        states = m._updater.states
        name = m._param_names[i]
        slot = opt_.slot_index(i, ndev, k)
        w = ex.arg_dict[name]
        if slot not in states:
            states[slot] = opt_.create_state_multi_precision(slot, w)
            m._updater.states_synced[slot] = True
        opt_._update_count(slot)
        return [(name, slot, opt_._get_lr(slot), opt_._get_wd(slot),
                 opt_._index_update_count[slot])]


class TrainerFusedUpdate:
    """Fused update phase for gluon.Trainer: one donated program per
    device replaces the per-param updater loop.  Weights are NOT donated
    (the autograd tape and user code may hold live references to
    ``param.data()`` buffers); optimizer state — which never escapes the
    updater un-copied — is."""

    def __init__(self, trainer):
        self._tr = trainer
        self._pools = [DonationPool() for _ in trainer._contexts]
        self._programs = {}
        self._unsupported = False

    def eligible(self):
        if not enabled() or self._unsupported:
            return False
        tr = self._tr
        if tr._update_on_kvstore:
            return False
        opt_ = tr._optimizer
        if opt_.fused_state_arity() is None:
            return False
        for p in tr._params:
            if p.grad_req == "null":
                continue
            if getattr(p, "_stype", "default") != "default" or \
                    getattr(p, "_grad_stype", "default") != "default":
                return False
            if not opt_.supports_fused(p.list_data()[0]):
                return False
        return True

    def step(self):
        from . import optimizer as _opt
        from . import profiler as _profiler
        tr = self._tr
        opt_ = tr._optimizer
        live = [(i, p) for i, p in enumerate(tr._params)
                if p.grad_req != "null"]
        if not live:
            return True
        arity = opt_.fused_state_arity()
        ncty = len(tr._contexts)
        per_dev = [{"p": [], "s": [], "g": [], "lr": [], "wd": [], "t": []}
                   for _ in range(ncty)]
        # eager order: param-major, device-minor — each device's updater
        # shares the optimizer, so the update count really does advance
        # once per (param, device) visit
        for i, p in live:
            datas, grads = p.list_data(), p.list_grad()
            for k, upd in enumerate(tr._updaters):
                w = datas[k]
                if i not in upd.states:
                    upd.states[i] = \
                        opt_.create_state_multi_precision(i, w)
                    upd.states_synced[i] = True
                leaves = _opt.fused_state_leaves(upd.states[i])
                if leaves is None or len(leaves) != arity:
                    self._unsupported = True
                    return False
                opt_._update_count(i)
                d = per_dev[k]
                d["p"].append(w._data)
                d["s"].append(tuple(self._pools[k].take((i, j), leaf)
                                    for j, leaf in enumerate(leaves)))
                d["g"].append([grads[k]._data])
                d["lr"].append(opt_._get_lr(i))
                d["wd"].append(opt_._get_wd(i))
                d["t"].append(opt_._index_update_count[i])
        rescale = jnp.asarray(opt_.rescale_grad, jnp.float32)
        env = _env_tuple()
        fn = self._programs.get(env)
        if fn is None:
            from .executor import build_update_program
            fn = build_update_program([opt_.fused_update] * len(live),
                                      donate_params=False)
            self._programs[env] = fn
        for k in range(ncty):
            d = per_dev[k]
            with _profiler.span("Trainer::FusedUpdate", "executor"):
                new_p, new_s = fn(
                    d["p"], d["s"], d["g"],
                    jnp.asarray(d["lr"], jnp.float32),
                    jnp.asarray(d["wd"], jnp.float32),
                    jnp.asarray(d["t"], jnp.float32), rescale)
            pool = self._pools[k]
            for (i, p), w, st in zip(live, new_p, new_s):
                p.list_data()[k]._data = w
                leaves = _opt.fused_state_leaves(tr._updaters[k].states[i])
                for j, (leaf, arr) in enumerate(zip(leaves, st)):
                    pool.give((i, j), leaf, arr)
        return True
