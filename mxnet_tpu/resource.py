"""Per-context operator resources: RNG streams and temp workspace.

Reference analog: the resource manager (``src/resource.cc``,
``include/mxnet/resource.h:42-46``) — per-device pools of op-requested
resources selected by ``ResourceRequest::Type``:

- ``kRandom``: per-device random generator, reseeded by ``mx.random.seed``
  (reference seeds every device generator from the global seed,
  ``resource.cc`` ``SeedRandom``).
- ``kTempSpace``: a dynamic scratch buffer of arbitrary size; the reference
  keeps ``MXNET_*_TEMP_COPIES`` rotating slots per device, shared between
  ops because its dependency engine serializes every user of a slot
  (``resource.h`` ``get_space`` contract).  Here slots are exclusive per
  granted Resource (host threads have no engine serializer) and reclaimed
  when the Resource is collected.
- ``kParallelRandom``: per-thread generator states usable inside kernels
  (``src/common/random_generator.h:45-97``).

TPU-native design: device-side temp space is owned by XLA's memory planner
(SURVEY.md §7.1 — PlanMemory is delegated), so ``kTempSpace`` here manages
*host* staging buffers (IO batch assembly, custom-op scratch) with the
reference's rotating-slot semantics.  RNG is functional threefry: a
``kRandom`` resource is a per-context key stream derived from the global
seed and the device id, and ``kParallelRandom`` returns keys the caller
``fold_in``s per lane — the functional analog of per-thread generator
states.  ``mxnet_tpu.random`` (the ``mx.random.seed`` UX) draws from this
manager's default-context stream, so every random op in the framework rides
these resources.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

import jax

from . import context as _context

__all__ = ["ResourceRequest", "Resource", "ResourceManager"]


class ResourceRequest:
    """The resource kinds an operator can request (resource.h:42-46)."""

    kRandom = 0
    kTempSpace = 1
    kParallelRandom = 2

    _NAMES = {0: "kRandom", 1: "kTempSpace", 2: "kParallelRandom"}

    def __init__(self, type):  # noqa: A002 - reference field name
        if type not in self._NAMES:
            raise ValueError("unknown ResourceRequest type %r" % (type,))
        self.type = type

    def __repr__(self):
        return "ResourceRequest(%s)" % self._NAMES[self.type]

    def __eq__(self, other):
        return isinstance(other, ResourceRequest) and other.type == self.type

    def __hash__(self):
        return hash(("ResourceRequest", self.type))


class _CtxState:
    """Per-context resource state: one key stream + temp-space slots.

    Temp-space slots are *exclusive* per granted Resource and reclaimed when
    the Resource is garbage-collected.  (The reference rotates
    ``MXNET_*_TEMP_COPIES`` shared slots because its dependency engine
    serializes every user of a slot — ``resource.cc``; host threads here
    have no such serializer, so sharing a slot between two independent
    resources would let concurrent producers corrupt each other's staging.)
    """

    def __init__(self, ctx: _context.Context, base_seed: int):
        self.ctx = ctx
        self.lock = threading.Lock()
        self.reseed(base_seed)
        # exclusive temp-space slots: slot id -> np buffer
        self._spaces: Dict[int, np.ndarray] = {}
        self._next_slot = 0
        self.space_reuses = 0
        self.space_allocs = 0

    def reseed(self, base_seed: int):
        # per-device stream: global seed folded with a stable device tag,
        # mirroring resource.cc seeding every device generator from the
        # global seed (distinct devices get distinct, reproducible streams)
        key = jax.random.PRNGKey(base_seed & 0x7FFFFFFF)
        folded = jax.random.fold_in(
            key, (self.ctx.device_typeid << 10) | self.ctx.device_id)
        with self.lock:
            self._key = folded

    def next_key(self):
        with self.lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def peek_key(self):
        with self.lock:
            return self._key

    def take_slot(self) -> int:
        with self.lock:
            slot = self._next_slot
            self._next_slot += 1
            return slot

    def release_slot(self, slot: int):
        # called from Resource.__del__, which cyclic GC may run on a thread
        # already inside a `with self.lock` block — dict.pop is GIL-atomic,
        # so stay lockless here to keep the finalizer deadlock-free
        self._spaces.pop(slot, None)

    def get_space(self, slot: int, shape, dtype) -> np.ndarray:
        """Scratch ndarray for one slot; grown monotonically, reused when it
        fits.  Callers serialize their own use of a slot (reference
        ``get_space`` contract: shared space, caller serializes)."""
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        with self.lock:
            buf = self._spaces.get(slot)
            if buf is None or buf.nbytes < nbytes:
                buf = np.empty((max(nbytes, 1),), np.uint8)
                self._spaces[slot] = buf
                self.space_allocs += 1
            else:
                self.space_reuses += 1
        return buf[:nbytes].view(dtype).reshape(shape)


class Resource:
    """One granted resource (resource.h ``struct Resource``)."""

    def __init__(self, req: ResourceRequest, state: _CtxState, rid: int):
        self.req = req
        self.id = rid
        self._state = state

    def __del__(self):
        # exclusive temp-space slots are reclaimed with their Resource
        try:
            if self.req.type == ResourceRequest.kTempSpace:
                self._state.release_slot(self.id)
        except Exception:
            pass  # interpreter shutdown

    @property
    def ctx(self):
        return self._state.ctx

    # ---- kRandom --------------------------------------------------------
    def get_random(self):
        """A fresh threefry subkey from this context's seeded stream
        (reference: ``get_random`` returns the per-device generator)."""
        if self.req.type != ResourceRequest.kRandom:
            raise TypeError("resource is %r, not kRandom" % (self.req,))
        return self._state.next_key()

    def peek_random(self):
        """The stream head without consuming a key (stable between draws)."""
        if self.req.type != ResourceRequest.kRandom:
            raise TypeError("resource is %r, not kRandom" % (self.req,))
        return self._state.peek_key()

    # ---- kParallelRandom ------------------------------------------------
    def get_parallel_random(self):
        """A base key to ``jax.random.fold_in`` per lane/thread — the
        functional analog of per-thread generator states
        (random_generator.h:45-97)."""
        if self.req.type != ResourceRequest.kParallelRandom:
            raise TypeError("resource is %r, not kParallelRandom" % (self.req,))
        return self._state.next_key()

    # ---- kTempSpace -----------------------------------------------------
    def get_space(self, shape, dtype=np.float32) -> np.ndarray:
        """Host scratch tensor of the requested shape.  The slot's buffer is
        reused across calls when it fits and grows otherwise; concurrent
        users of the *same* Resource must serialize (reference contract)."""
        if self.req.type != ResourceRequest.kTempSpace:
            raise TypeError("resource is %r, not kTempSpace" % (self.req,))
        return self._state.get_space(self.id, shape, dtype)


class ResourceManager:
    """Singleton granting per-context resources (``ResourceManager::Get``)."""

    _instance: Optional["ResourceManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._states: Dict[Tuple[int, int], _CtxState] = {}
        self._seed = 0

    @classmethod
    def get(cls) -> "ResourceManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _state_for(self, ctx: _context.Context) -> _CtxState:
        key = (ctx.device_typeid, ctx.device_id)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = _CtxState(ctx, self._seed)
                self._states[key] = st
            return st

    def request(self, ctx: Optional[_context.Context],
                req: ResourceRequest) -> Resource:
        """Grant a resource on ``ctx`` (default: current context)."""
        if isinstance(req, int):
            req = ResourceRequest(req)
        ctx = ctx or _context.current_context()
        st = self._state_for(ctx)
        rid = st.take_slot() if req.type == ResourceRequest.kTempSpace else 0
        return Resource(req, st, rid)

    def seed(self, seed_state: int, ctx: Optional[_context.Context] = None):
        """Reseed RNG streams from a seed (``mx.random.seed`` semantics).

        ``ctx=None`` reseeds every context from the global seed (resource.cc
        SeedRandom); a specific ``ctx`` reseeds only that device's stream
        (reference ``mx.random.seed(s, ctx=...)`` per-device seeding).
        """
        s = int(seed_state) & 0x7FFFFFFF
        if ctx is not None:
            self._state_for(ctx).reseed(s)
            return
        with self._lock:
            self._seed = s
            states = list(self._states.values())
        for st in states:
            st.reseed(s)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-context temp-space pool counters (debug/observability)."""
        with self._lock:
            return {
                repr(st.ctx): {"space_allocs": st.space_allocs,
                               "space_reuses": st.space_reuses,
                               "live_slots": len(st._spaces)}
                for st in self._states.values()
            }
