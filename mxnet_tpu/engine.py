"""Dependency-scheduling engine, TPU-native.

Re-design of the reference dependency engine (``src/engine/threaded_engine.{h,cc}``,
``threaded_engine_perdevice.cc``, ``naive_engine.cc``; interface
``include/mxnet/engine.h:134-213``).

Division of labor on TPU: *device* asynchrony (kernel launch, overlap of
compute with ICI collectives and HBM traffic) is owned by XLA/PjRt — every op
dispatched through JAX is already async and ordered per-buffer by the runtime,
so NDArray compute does NOT need a host scheduler to be parallel.  What still
needs the reference's var-dependency protocol is *host-side* work: data
pipeline decode/augment, KVStore host reductions, checkpoint writes, custom
Python ops — anything that must overlap with device compute while respecting
read/write ordering on shared state.  This module keeps the reference Engine
contract (NewVariable / NewOperator / Push / WaitForVar / WaitForAll, plus
async exception propagation, SURVEY.md §5.2) for that host-side work, with the
same two personalities:

- ``NaiveEngine``: synchronous, deterministic (``MXNET_ENGINE_TYPE=NaiveEngine``
  debug mode, reference ``engine.cc:40``).
- ``ThreadedEngine``: a thread pool executing ops when their var deps resolve,
  the analog of ``ThreadedEnginePerDevice`` with its per-var queues of
  ``VersionedVarBlock`` (``threaded_engine.h:99-116``).
"""
from __future__ import annotations

import collections
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence

from .base import get_env
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["Var", "Engine", "NaiveEngine", "ThreadedEngine", "get", "set_engine"]

# Bound label children cached at module scope so the enabled hot path pays
# one attribute check + one locked add per event (disabled: attribute
# check only — the bench-critical fast path).
_OPS_PUSHED = _telemetry.counter(
    "engine_ops_pushed_total",
    "Operations pushed to the dependency engine", ("engine",))
_OPS_DONE = _telemetry.counter(
    "engine_ops_completed_total",
    "Operations completed by the dependency engine", ("engine",))
_QUEUE_DEPTH = _telemetry.gauge(
    "engine_queue_depth",
    "Engine ops in flight (pushed but not yet completed)", ("engine",))
_DISPATCH_LAT = _telemetry.histogram(
    "engine_dispatch_latency_seconds",
    "Delay between push and execution start (dependency wait + queueing)",
    ("engine",))
_WORKERS_BUSY = _telemetry.gauge(
    "engine_workers_busy", "Worker threads currently executing an op")
_WORKERS_TOTAL = _telemetry.gauge(
    "engine_workers_total", "Size of the engine worker pool")

_T_PUSHED = _OPS_PUSHED.labels(engine="threaded")
_T_DONE = _OPS_DONE.labels(engine="threaded")
_T_DEPTH = _QUEUE_DEPTH.labels(engine="threaded")
_T_DISPATCH = _DISPATCH_LAT.labels(engine="threaded")
_N_PUSHED = _OPS_PUSHED.labels(engine="naive")
_N_DONE = _OPS_DONE.labels(engine="naive")
_NAT_PUSHED = _OPS_PUSHED.labels(engine="native")
_NAT_DONE = _OPS_DONE.labels(engine="native")
_NAT_DEPTH = _QUEUE_DEPTH.labels(engine="native")


class Var:
    """An engine variable: a serialization point for reads/writes.

    Analog of ``ThreadedVar`` (``threaded_engine.h:99``).  Scheduling protocol
    (mirrors ``AppendRead/WriteDependency`` + ``CompleteRead/WriteDependency``,
    ``threaded_engine.cc:51-143``): requests queue FIFO; the head is granted
    when it is a read and no write is currently granted, or a write and
    nothing is granted; consecutive reads at the head are granted together.
    Exceptions raised by an op are stored and re-thrown at the next
    ``wait_to_read``-style sync, matching the reference's
    ``std::exception_ptr`` propagation (``threaded_engine.cc:466-468``).
    """

    __slots__ = ("queue", "granted_reads", "granted_write", "exc", "name")

    def __init__(self, name: str = ""):
        self.queue = collections.deque()  # of (opr, is_write) in push order
        self.granted_reads = 0
        self.granted_write = False
        self.exc: Optional[BaseException] = None
        self.name = name

    def __repr__(self):
        return "Var(%s)" % (self.name,)


class _OprBlock:
    """Analog of ``OprBlock`` (``threaded_engine.h:66``)."""

    __slots__ = ("fn", "const_vars", "mutable_vars", "wait", "name", "exc",
                 "done", "t_push", "trace")

    def __init__(self, fn, const_vars, mutable_vars, name):
        self.fn = fn
        self.const_vars = const_vars
        self.mutable_vars = mutable_vars
        self.wait = 0  # vars that have not yet granted this op
        self.name = name
        self.exc: Optional[BaseException] = None
        self.done = threading.Event()
        self.t_push = 0.0  # set at push only when telemetry is enabled
        self.trace = None  # _tracing._EngineFlow when tracing is enabled


class Engine:
    """Engine interface (reference ``include/mxnet/engine.h``)."""

    def new_variable(self, name: str = "") -> Var:
        return Var(name)

    def push(self, fn: Callable[[], None], const_vars: Sequence[Var] = (),
             mutable_vars: Sequence[Var] = (), name: str = "") -> None:
        raise NotImplementedError

    def push_sync(self, fn, const_vars=(), mutable_vars=(), name=""):
        """Push and block until fn completes (reference Engine::PushSync)."""
        self.push(fn, const_vars, mutable_vars, name)
        for v in mutable_vars:
            self.wait_for_var(v)

    def wait_for_var(self, var: Var) -> None:
        raise NotImplementedError

    def wait_for_all(self) -> None:
        raise NotImplementedError

    def delete_variable(self, var: Var) -> None:
        """Reference ``DeleteVariable``: GC of vars is automatic in Python."""

    def stop(self):
        pass

    def start(self):
        pass


class NaiveEngine(Engine):
    """Synchronous engine: ops run inline at push (``naive_engine.cc``)."""

    def push(self, fn, const_vars=(), mutable_vars=(), name=""):
        if _telemetry.enabled:
            _N_PUSHED.inc()
        for v in tuple(const_vars) + tuple(mutable_vars):
            if v.exc is not None:
                raise v.exc
        tr = None
        if _tracing.enabled:
            tr = _tracing.engine_push(name, const_vars, mutable_vars)
            tr.pushed()
            tr.exec_begin()
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - must propagate like ref
            for v in mutable_vars:
                v.exc = e
            _tracing.flight.on_engine_crash(
                name, e, [_tracing._var_name(v) for v in mutable_vars])
            raise
        finally:
            if tr is not None:
                tr.exec_end()
                tr.completed()
            if _telemetry.enabled:
                _N_DONE.inc()

    def wait_for_var(self, var):
        if var.exc is not None:
            exc, var.exc = var.exc, None
            raise exc

    def wait_for_all(self):
        pass


class ThreadedEngine(Engine):
    """Threaded var-dependency scheduler (see Var docstring for protocol)."""

    def __init__(self, num_workers: Optional[int] = None):
        n = num_workers or get_env("MXNET_CPU_WORKER_NTHREADS",
                                   min(16, os.cpu_count() or 4), int)
        self._pool = ThreadPoolExecutor(max_workers=n,
                                        thread_name_prefix="mxtpu-engine")
        self._lock = threading.Lock()  # guards all var state + counters
        self._inflight = 0
        self._idle = threading.Condition(self._lock)
        if _telemetry.enabled:
            _WORKERS_TOTAL.set(n)

    def push(self, fn, const_vars=(), mutable_vars=(), name=""):
        self._push(fn, const_vars, mutable_vars, name)

    def _push(self, fn, const_vars=(), mutable_vars=(), name=""):
        mvars = list(dict.fromkeys(mutable_vars))
        cvars = [v for v in dict.fromkeys(const_vars) if v not in mvars]
        opr = _OprBlock(fn, cvars, mvars, name)
        if _tracing.enabled:
            opr.trace = _tracing.engine_push(name, cvars, mvars)
        if _telemetry.enabled:
            opr.t_push = time.perf_counter()
            _T_PUSHED.inc()
        to_run: List[_OprBlock] = []
        with self._lock:
            self._inflight += 1
            if _telemetry.enabled:
                _T_DEPTH.set(self._inflight)
            opr.wait = len(cvars) + len(mvars)
            for v in cvars:
                v.queue.append((opr, False))
            for v in mvars:
                v.queue.append((opr, True))
            if opr.wait == 0:  # no deps at all
                to_run.append(opr)
            for v in cvars + mvars:
                self._try_grant(v, to_run)
        if opr.trace is not None:
            # flow-start before any worker can emit the flow-step
            opr.trace.pushed()
        for o in to_run:
            self._pool.submit(self._execute, o)
        return opr

    def _try_grant(self, var: Var, to_run: List[_OprBlock]):
        """Grant queue heads per reader/writer rules; caller holds _lock."""
        while var.queue:
            opr, is_write = var.queue[0]
            if is_write:
                if var.granted_reads > 0 or var.granted_write:
                    break
                var.granted_write = True
            else:
                if var.granted_write:
                    break
                var.granted_reads += 1
            var.queue.popleft()
            opr.wait -= 1
            if opr.wait == 0:
                to_run.append(opr)
            if is_write:
                break

    def _execute(self, opr: _OprBlock):
        tel = _telemetry.enabled  # one sample: pair the inc with its dec
        tr = opr.trace
        if tel:
            if opr.t_push:
                _T_DISPATCH.observe(time.perf_counter() - opr.t_push)
            _WORKERS_BUSY.inc()
        if tr is not None:
            tr.exec_begin()
        try:
            for v in opr.const_vars + opr.mutable_vars:
                if v.exc is not None:
                    raise v.exc
            opr.fn()
        except BaseException as e:  # noqa: BLE001
            opr.exc = e
            # a dump only for the crash origin — ops failing because a
            # dependency poisoned them would re-dump the same root cause
            propagated = any(v.exc is e
                             for v in opr.const_vars + opr.mutable_vars)
            for v in opr.mutable_vars:
                v.exc = e
            if not propagated:
                _tracing.flight.on_engine_crash(
                    opr.name, e, opr.trace.mutable_names if opr.trace
                    else [_tracing._var_name(v) for v in opr.mutable_vars])
        finally:
            if tr is not None:
                tr.exec_end(error=opr.exc)
            if tel:
                _WORKERS_BUSY.dec()
                _T_DONE.inc()
            self._on_complete(opr)

    def _on_complete(self, opr: _OprBlock):
        """Analog of ``ThreadedEngine::OnComplete`` (threaded_engine.cc:412)."""
        if opr.trace is not None:
            # before the inflight decrement: wait_for_all returning must
            # imply the flow-end is already in the event stream
            opr.trace.completed()
        to_run: List[_OprBlock] = []
        with self._lock:
            for v in opr.const_vars:
                v.granted_reads -= 1
                self._try_grant(v, to_run)
            for v in opr.mutable_vars:
                v.granted_write = False
                self._try_grant(v, to_run)
            self._inflight -= 1
            if _telemetry.enabled:
                _T_DEPTH.set(self._inflight)
            if self._inflight == 0:
                self._idle.notify_all()
        opr.done.set()
        for o in to_run:
            self._pool.submit(self._execute, o)

    def push_sync(self, fn, const_vars=(), mutable_vars=(), name=""):
        """Push and block until fn itself completes — including const-only
        ops (reference Engine::PushSync semantics)."""
        opr = self._push(fn, const_vars, mutable_vars, name)
        opr.done.wait()
        if opr.exc is not None:
            raise opr.exc

    def wait_for_var(self, var: Var):
        # push a no-op read; once it completes, all prior writes are done.
        opr = self._push(lambda: None, const_vars=(var,), name="WaitForVar")
        opr.done.wait()
        if var.exc is not None:
            exc, var.exc = var.exc, None
            raise exc

    def wait_for_all(self):
        with self._idle:
            while self._inflight > 0:
                self._idle.wait()

    def stop(self):
        self._pool.shutdown(wait=True)


class NativeVar:
    """A var owned by the C++ engine (wraps the native handle)."""

    __slots__ = ("handle", "name", "exc")

    def __init__(self, handle, name=""):
        self.handle = handle
        self.name = name
        self.exc = None  # API parity; native errors surface at wait

    def __repr__(self):
        return "<NativeVar %s>" % (self.name or hex(self.handle or 0))


class NativeThreadedEngine(Engine):
    """The C++ threaded dependency engine (src/engine.cc) driven over the
    ctypes C ABI — the default, ``ThreadedEnginePerDevice``-equivalent
    backend.  Python callbacks run on the C++ worker threads (ctypes
    acquires the GIL per call); exceptions are mapped to integer codes that
    poison vars native-side and are re-raised at ``wait_for_var``."""

    MAX_STORED_ERRORS = 1024  # bound on never-surfaced exception objects

    def __init__(self, num_workers: Optional[int] = None):
        import atexit
        import ctypes
        from . import _native
        self._lib = _native.lib()
        if self._lib is None:
            raise RuntimeError("native engine library unavailable")
        n = num_workers or get_env("MXNET_CPU_WORKER_NTHREADS",
                                   min(16, os.cpu_count() or 4), int)
        self._handle = self._lib.MXNativeEngineCreate(int(n))
        self._errors = collections.OrderedDict()  # error code -> exception
        self._pending = {}  # payload key -> (fn, done, t_push, trace)
        self._next = [1]
        self._lock = threading.Lock()
        eng = self

        @ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64)
        def _trampoline(key, prior_err):
            # ALWAYS called — even when a poisoned dependency means the user
            # fn is skipped — so closure state is released and push_sync
            # waiters are woken (src/engine.cc Execute contract)
            with eng._lock:
                fn, done, t_push, tr = eng._pending.pop(key)
                depth = len(eng._pending)
            if _telemetry.enabled:
                if t_push:
                    _DISPATCH_LAT.labels(engine="native").observe(
                        time.perf_counter() - t_push)
                _NAT_DEPTH.set(depth)
            if tr is not None:
                tr.exec_begin()
            code = int(prior_err)
            err = None
            if code == 0:
                try:
                    fn()
                except BaseException as e:  # noqa: BLE001 - ref propagates
                    err = e
                    with eng._lock:
                        code = eng._next[0]
                        eng._next[0] += 1
                        eng._errors[code] = e
                        while len(eng._errors) > eng.MAX_STORED_ERRORS:
                            eng._errors.popitem(last=False)
            if tr is not None:
                tr.exec_end(error=err)
                tr.completed()
            if err is not None:
                _tracing.flight.on_engine_crash(
                    tr.name if tr is not None else "native_engine_op", err,
                    tr.mutable_names if tr is not None else None)
            if done is not None:
                done.code = code
                done.set()
            if _telemetry.enabled:
                _NAT_DONE.inc()
            return code

        self._trampoline = _trampoline  # keep alive
        self._fn_ptr = ctypes.cast(_trampoline, ctypes.c_void_p)
        # drain pending host work before interpreter teardown: the C++
        # workers are invisible to Python's threading shutdown, and a
        # trampoline call after finalization would crash (the Python
        # ThreadedEngine got this for free from ThreadPoolExecutor join)
        atexit.register(self._drain_at_exit)

    def _drain_at_exit(self):
        if self._handle:
            self._lib.MXNativeEngineWaitForAll(self._handle)
            self.stop()

    def new_variable(self, name: str = "") -> NativeVar:
        return NativeVar(self._lib.MXNativeEngineNewVar(self._handle), name)

    def _var_array(self, vars_):
        import ctypes
        arr = (ctypes.c_void_p * max(1, len(vars_)))()
        for i, v in enumerate(vars_):
            arr[i] = v.handle
        return arr

    def _push(self, fn, const_vars, mutable_vars, done=None, prio=0, name=""):
        mvars = list(dict.fromkeys(mutable_vars))
        cvars = [v for v in dict.fromkeys(const_vars) if v not in mvars]
        tel = _telemetry.enabled
        if tel:
            _NAT_PUSHED.inc()
        tr = None
        if _tracing.enabled:
            tr = _tracing.engine_push(name, cvars, mvars)
        with self._lock:
            key = self._next[0]
            self._next[0] += 1
            self._pending[key] = (fn, done,
                                  time.perf_counter() if tel else 0.0, tr)
            if tel:
                _NAT_DEPTH.set(len(self._pending))
        if tr is not None:
            tr.pushed()
        self._lib.MXNativeEnginePush(
            self._handle, self._fn_ptr, key,
            self._var_array(cvars), len(cvars),
            self._var_array(mvars), len(mvars), prio)

    def push(self, fn, const_vars=(), mutable_vars=(), name=""):
        self._push(fn, const_vars, mutable_vars, name=name)

    def push_sync(self, fn, const_vars=(), mutable_vars=(), name=""):
        done = threading.Event()
        done.code = 0
        self._push(fn, const_vars, mutable_vars, done=done, name=name)
        done.wait()
        if done.code:
            with self._lock:
                # peek, don't pop: the poisoned var still owns this error
                # until a wait_for_var surfaces (and clears) it
                exc = self._errors.get(done.code)
            if exc is not None:
                raise exc

    def wait_for_var(self, var: NativeVar):
        code = self._lib.MXNativeEngineWaitForVar(self._handle, var.handle)
        if code:
            with self._lock:
                # peek, don't pop: one failing op may have poisoned several
                # vars sharing this code; entries age out of the bounded
                # OrderedDict instead
                exc = self._errors.get(code)
            if exc is not None:
                raise exc
            raise RuntimeError("engine op failed (code %d; original "
                               "exception aged out)" % code)

    def wait_for_all(self):
        self._lib.MXNativeEngineWaitForAll(self._handle)

    def delete_variable(self, var: NativeVar):
        self._lib.MXNativeEngineDeleteVar(self._handle, var.handle)
        var.handle = None

    def stop(self):
        if self._handle:
            self._lib.MXNativeEngineFree(self._handle)
            self._handle = None


_engine: Optional[Engine] = None
_engine_lock = threading.Lock()


def get() -> Engine:
    """Singleton accessor (reference ``Engine::Get``), selected by
    ``MXNET_ENGINE_TYPE`` just like ``engine.cc:32-47``:
    NaiveEngine | ThreadedEngine (python pool) | ThreadedEnginePerDevice
    (default; the native C++ engine, falling back to the Python pool when
    no toolchain is available)."""
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                kind = get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
                lower = kind.lower()
                if "naive" in lower:
                    _engine = NaiveEngine()
                elif lower == "threadedengine":
                    _engine = ThreadedEngine()
                else:
                    try:
                        _engine = NativeThreadedEngine()
                    except RuntimeError:
                        _engine = ThreadedEngine()
    return _engine


def set_engine(engine: Engine):
    global _engine
    _engine = engine


def _at_fork_child():
    """Fork survival (reference initialize.cc:39-70 pthread_atfork: the
    engine is stopped before fork and restarted in both processes so
    fork-based DataLoader workers can't deadlock on dead worker threads).
    Python threads don't survive fork, so the child must drop the
    inherited singleton — the next get() builds a fresh engine."""
    global _engine
    _engine = None


def _before_fork():
    """Drain the queue so the child never sees half-scheduled vars."""
    if _engine is not None:
        try:
            _engine.wait_for_all()
        except Exception:
            pass  # fork must not be blocked by a poisoned op


try:
    import os as _os
    _os.register_at_fork(before=_before_fork,
                         after_in_child=_at_fork_child)
except (ImportError, AttributeError):  # non-POSIX: no fork, nothing to do
    pass
