"""Object-detection data pipeline: det augmenters + ImageDetIter.

Parity targets:
  - python/mxnet/image/detection.py:625 (``ImageDetIter`` — variable-count
    padded label format, IoU-constrained random crop, geometric label
    updates, label-shape estimation/sync)
  - src/io/iter_image_det_recordio.cc:582 (``ImageDetRecordIter`` — the
    C++ record iterator; here the same record format is served by
    :class:`ImageDetIter` over ``.rec`` + a padded-width variant in io.py)

Label wire format (reference detection.py:710 ``_parse_label``)::

    [header_width, obj_width, (extra header...), obj0..., obj1..., ...]

where each object is ``[class_id, xmin, ymin, xmax, ymax, ...]`` with
coordinates normalized to [0, 1].  Batch labels are padded with -1 rows to
the estimated max object count.
"""
from __future__ import annotations

import json
import logging
import random

import numpy as np

from .base import MXNetError
from . import io as _io
from . import ndarray as nd
from .image import (Augmenter, ResizeAug, ForceResizeAug, CastAug,
                    ColorJitterAug, HueJitterAug, LightingAug, RandomGrayAug,
                    ColorNormalizeAug, fixed_crop, ImageIter)
from .ndarray.ndarray import NDArray

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base: __call__(src, label) -> (src, label)."""

    def __init__(self, **kwargs):
        self._kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                v = v.asnumpy()
            if isinstance(v, np.ndarray):
                v = v.tolist()
            self._kwargs[k] = v

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a classification augmenter that cannot affect labels
    (ref detection.py:74)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("Borrowing from invalid Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def dumps(self):
        return [self.__class__.__name__.lower(), self.augmenter.dumps()]

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply exactly one augmenter from a list, or skip all
    (ref detection.py:100)."""

    def __init__(self, aug_list, skip_prob=0):
        super().__init__(skip_prob=skip_prob)
        if not isinstance(aug_list, (list, tuple)):
            aug_list = [aug_list]
        for aug in aug_list:
            if not isinstance(aug, DetAugmenter):
                raise ValueError("Allow DetAugmenter in list only")
        if not aug_list:
            skip_prob = 1
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def dumps(self):
        return [self.__class__.__name__.lower(),
                [x.dumps() for x in self.aug_list]]

    def __call__(self, src, label):
        if random.random() < self.skip_prob:
            return src, label
        return random.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Random horizontal flip, mirroring xmin/xmax (ref detection.py:128)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = nd.array(np.ascontiguousarray(_asnp(src)[:, ::-1]))
            tmp = 1.0 - label[:, 1]
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


def _asnp(src):
    return src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)


def _box_areas(boxes):
    """(N,4+) normalized [xmin,ymin,xmax,ymax] -> areas."""
    h = np.maximum(0, boxes[:, 3] - boxes[:, 1])
    w = np.maximum(0, boxes[:, 2] - boxes[:, 0])
    return h * w


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (ref detection.py:152 — SSD-style
    sampling: every surviving object must be covered at least
    ``min_object_covered``; objects reduced below ``min_eject_coverage``
    of their original area are ejected)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.enabled = (area_range[1] > 0 and
                        area_range[0] <= area_range[1] and
                        0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        crop = self._random_crop_proposal(label, src.shape[0], src.shape[1])
        if crop:
            x, y, w, h, label = crop
            src = fixed_crop(src, x, y, w, h, None)
        return src, label

    def _intersect(self, boxes, xmin, ymin, xmax, ymax):
        left = np.maximum(boxes[:, 0], xmin)
        right = np.minimum(boxes[:, 2], xmax)
        top = np.maximum(boxes[:, 1], ymin)
        bot = np.minimum(boxes[:, 3], ymax)
        invalid = np.where(np.logical_or(left >= right, top >= bot))[0]
        out = boxes.copy()
        out[:, 0], out[:, 1], out[:, 2], out[:, 3] = left, top, right, bot
        out[invalid, :] = 0
        return out

    def _check_satisfy_constraints(self, label, xmin, ymin, xmax, ymax,
                                   width, height):
        if (xmax - xmin) * (ymax - ymin) < 2:
            return False
        x1, y1 = float(xmin) / width, float(ymin) / height
        x2, y2 = float(xmax) / width, float(ymax) / height
        object_areas = _box_areas(label[:, 1:])
        valid_objects = np.where(object_areas * width * height > 2)[0]
        if valid_objects.size < 1:
            return False
        intersects = self._intersect(label[valid_objects, 1:], x1, y1, x2, y2)
        coverages = _box_areas(intersects) / object_areas[valid_objects]
        coverages = coverages[np.where(coverages > 0)[0]]
        return coverages.size > 0 and \
            np.amin(coverages) > self.min_object_covered

    def _update_labels(self, label, crop_box, height, width):
        xmin = float(crop_box[0]) / width
        ymin = float(crop_box[1]) / height
        w = float(crop_box[2]) / width
        h = float(crop_box[3]) / height
        out = label.copy()
        out[:, (1, 3)] -= xmin
        out[:, (2, 4)] -= ymin
        out[:, (1, 3)] /= w
        out[:, (2, 4)] /= h
        out[:, 1:5] = np.clip(out[:, 1:5], 0, 1)
        coverage = _box_areas(out[:, 1:]) * w * h / _box_areas(label[:, 1:])
        valid = np.logical_and(out[:, 3] > out[:, 1], out[:, 4] > out[:, 2])
        valid = np.logical_and(valid, coverage > self.min_eject_coverage)
        valid = np.where(valid)[0]
        if valid.size < 1:
            return None
        return out[valid, :]

    def _random_crop_proposal(self, label, height, width):
        from math import sqrt

        if not self.enabled or height <= 0 or width <= 0:
            return ()
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = random.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(max_h * ratio) > width:
                max_h = int((width + 0.4999999) / ratio)
            max_h = min(max_h, height)
            h = min(h, max_h)
            if h < max_h:
                h = random.randint(h, max_h)
            w = int(round(h * ratio))
            if w > width:
                continue
            area = w * h
            if area < min_area:
                h += 1
                w = int(round(h * ratio))
                area = w * h
            if area > max_area:
                h -= 1
                w = int(round(h * ratio))
                area = w * h
            if (area < min_area or area > max_area or w > width or
                    h > height or w <= 0 or h <= 0):
                continue
            y = random.randint(0, max(0, height - h))
            x = random.randint(0, max(0, width - w))
            if self._check_satisfy_constraints(label, x, y, x + w, y + h,
                                               width, height):
                new_label = self._update_labels(label, (x, y, w, h),
                                                height, width)
                if new_label is not None:
                    return (x, y, w, h, new_label)
        return ()


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding (ref detection.py:338 — place the image in
    a larger canvas, rescaling labels; SSD zoom-out augmentation)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (list, tuple)):
            pad_val = (pad_val,)
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0 and
                        area_range[0] <= area_range[1] and
                        0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        height, width = src.shape[0], src.shape[1]
        pad = self._random_pad_proposal(label, height, width)
        if pad:
            x, y, w, h, label = pad
            arr = _asnp(src)
            canvas = np.empty((h, w, arr.shape[2]), arr.dtype)
            canvas[...] = np.asarray(
                self.pad_val, arr.dtype)[:arr.shape[2]]
            canvas[y:y + height, x:x + width] = arr
            src = nd.array(canvas)
        return src, label

    def _update_labels(self, label, pad_box, height, width):
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] * width + pad_box[0]) / pad_box[2]
        out[:, (2, 4)] = (out[:, (2, 4)] * height + pad_box[1]) / pad_box[3]
        return out

    def _random_pad_proposal(self, label, height, width):
        from math import sqrt

        if not self.enabled or height <= 0 or width <= 0:
            return ()
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = random.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(h * ratio) < width:
                h = int((width + 0.499999) / ratio)
            h = max(h, height)
            h = min(h, max_h)
            if h < max_h:
                h = random.randint(h, max_h)
            w = int(round(h * ratio))
            if (h - height) < 2 or (w - width) < 2:
                continue
            y = random.randint(0, max(0, h - height))
            x = random.randint(0, max(0, w - width))
            new_label = self._update_labels(label, (x, y, w, h),
                                            height, width)
            return (x, y, w, h, new_label)
        return ()


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """List-valued params broadcast into several crop augmenters, one of
    which is randomly selected per image (ref detection.py:418)."""
    def align(params):
        out, num = [], 1
        for p in params:
            p = p if isinstance(p, list) else [p]
            out.append(p)
            num = max(num, len(p))
        for k, p in enumerate(out):
            if len(p) != num:
                assert len(p) == 1, "cannot broadcast param of len %d" % len(p)
                out[k] = p * num
        return out

    aligned = align([min_object_covered, aspect_ratio_range, area_range,
                     min_eject_coverage, max_attempts])
    augs = [DetRandomCropAug(min_object_covered=moc, aspect_ratio_range=arr,
                             area_range=ar, min_eject_coverage=mec,
                             max_attempts=ma)
            for moc, arr, ar, mec, ma in zip(*aligned)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Detection augmenter pipeline factory (ref detection.py:483)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range, area_range,
            min_eject_coverage, max_attempts, skip_prob=(1 - rand_crop)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad_aug = DetRandomPadAug(aspect_ratio_range, (1.0, area_range[1]),
                                  max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad_aug], 1 - rand_pad))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = np.asarray(mean)
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = np.asarray(std)
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection image iterator over .rec/.lst sources (ref
    detection.py:625): parses the header-prefixed variable-count label
    format, applies det augmenters, and pads batch labels with -1."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        if aug_list is None:
            self.auglist = CreateDetAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.label_pad_value = -1.0
        self.label_shape = self._estimate_label_shape()

    # parent exposes provide_label as a property; detection labels are
    # (batch, max_objects, obj_width)
    @property
    def provide_label(self):
        return [_io.DataDesc(
            self._label_name,
            (self.batch_size,) + tuple(self.label_shape), np.float32)]

    @provide_label.setter
    def provide_label(self, descs):
        (name, shape) = descs[0][:2]
        self._label_name = name
        self.label_shape = tuple(shape[1:])

    def _check_valid_label(self, label):
        if len(label.shape) != 2 or label.shape[1] < 5:
            raise MXNetError("Label with shape (1+, 5+) required, %s "
                             "received." % str(label))
        valid = np.where(np.logical_and(label[:, 0] >= 0,
                                        np.logical_and(
                                            label[:, 3] > label[:, 1],
                                            label[:, 4] > label[:, 2])))[0]
        if valid.size < 1:
            raise MXNetError("Invalid label occurs.")

    def _estimate_label_shape(self):
        max_count, width = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self.next_sample()
                label = self._parse_label(label)
                max_count = max(max_count, label.shape[0])
                width = label.shape[1]
        except StopIteration:
            pass
        self.reset()
        return (max_count, width)

    def _parse_label(self, label):
        """[header_width, obj_width, ...header, objs...] -> (N, obj_width)
        with degenerate boxes removed (ref detection.py:710)."""
        if isinstance(label, NDArray):
            label = label.asnumpy()
        raw = np.asarray(label, np.float32).ravel()
        if raw.size < 7:
            raise MXNetError("Label shape is invalid: " + str(raw.shape))
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if (raw.size - header_width) % obj_width != 0:
            raise MXNetError("Label shape %s inconsistent with annotation "
                             "width %d." % (str(raw.shape), obj_width))
        out = np.reshape(raw[header_width:], (-1, obj_width))
        valid = np.where(np.logical_and(out[:, 3] > out[:, 1],
                                        out[:, 4] > out[:, 2]))[0]
        if valid.size < 1:
            raise MXNetError("Encounter sample with no valid label.")
        return out[valid, :]

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.check_data_shape(data_shape)
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self.check_label_shape(label_shape)
            self.label_shape = tuple(label_shape)

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, c, h, w), np.float32)
        batch_label = np.full((batch_size,) + tuple(self.label_shape),
                              self.label_pad_value, np.float32)
        i = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                from .image import imdecode
                data = imdecode(s)
                try:
                    label = self._parse_label(label)
                    data, label = self.augmentation_transform(data, label)
                    self._check_valid_label(label)
                except MXNetError as e:
                    logging.debug("Invalid image, skipping: %s", str(e))
                    continue
                arr = _asnp(data)
                batch_data[i] = arr.transpose(2, 0, 1)
                num_object = min(label.shape[0], self.label_shape[0])
                batch_label[i, :num_object, :label.shape[1]] = \
                    label[:num_object]
                i += 1
        except StopIteration:
            if not i:
                raise
        return _io.DataBatch([nd.array(batch_data)],
                             [nd.array(batch_label)],
                             pad=batch_size - i)

    __next__ = next

    def augmentation_transform(self, data, label):
        for aug in self.auglist:
            data, label = aug(data, label)
        return data, label

    def check_data_shape(self, data_shape):
        if not len(data_shape) == 3:
            raise ValueError("data_shape should have length 3")
        if not data_shape[0] == 3:
            raise ValueError("This iterator expects RGB data (3 channels)")

    def check_label_shape(self, label_shape):
        if not len(label_shape) == 2:
            raise ValueError("label_shape should have length 2")
        if label_shape[0] < self.label_shape[0]:
            raise ValueError(
                "Attempts to reduce label count from %d to %d, not allowed."
                % (self.label_shape[0], label_shape[0]))

    def sync_label_shape(self, it, verbose=False):
        """Synchronize label padding between train/val iterators
        (ref detection.py:901)."""
        assert isinstance(it, ImageDetIter), "only applies to ImageDetIter"
        train_shape = self.label_shape
        val_shape = it.label_shape
        assert train_shape[1] == val_shape[1], "object widths mismatch"
        max_count = max(train_shape[0], val_shape[0])
        if max_count > train_shape[0]:
            self.reshape(None, (max_count, train_shape[1]))
        if max_count > val_shape[0]:
            it.reshape(None, (max_count, val_shape[1]))
        if verbose and max_count > min(train_shape[0], val_shape[0]):
            logging.info("Resized label_shape to (%d, %d).",
                         max_count, train_shape[1])
        return self
