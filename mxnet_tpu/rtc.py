"""Runtime kernel compilation (parity: ``python/mxnet/rtc.py`` over
SURVEY.md N21).

Reference analog: ``CudaModule``/``CudaKernel`` (include/mxnet/rtc.h:39-118,
src/common/rtc.cc) — the user supplies CUDA C source at runtime, NVRTC
compiles it, and the kernel launches on NDArrays from Python.

TPU-native equivalent: the user supplies **Pallas** kernel source (Python,
using ``jax.experimental.pallas``) — the TPU's runtime-compilation path.
``PallasModule(source).get_kernel(name, out_shape=..., out_dtype=...)``
returns a launchable kernel; ``kernel.launch(args, grid=...)`` runs it on
NDArrays, compiling on first use (XLA/Mosaic), exactly the CudaModule
ergonomics with the vendor compiler swapped for Mosaic.  ``CudaModule`` is
kept as a hard-erroring alias so reference code fails with a clear message.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from .base import MXNetError

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


class PallasModule:
    """A module of Pallas kernels compiled from Python source or given as
    callables (the CudaModule analog)."""

    def __init__(self, source=None, exports=(), functions=None):
        self._fns: Dict[str, object] = {}
        if functions:
            self._fns.update(functions)
        if source is not None:
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            ns = {"jax": jax, "jnp": jnp, "pl": pl, "np": np}
            try:
                from jax.experimental.pallas import tpu as pltpu
                ns["pltpu"] = pltpu
            except ImportError:
                pass
            preset = set(ns)
            exec(compile(source, "<pallas_module>", "exec"), ns)
            names = list(exports) if exports else \
                [k for k, v in ns.items()
                 if k not in preset and not k.startswith("_")
                 and callable(v)]
            for name in names:
                if name not in ns or not callable(ns[name]):
                    raise MXNetError("exported kernel %r not found in "
                                     "module source" % name)
                self._fns[name] = ns[name]

    def get_kernel(self, name, out_shape=None, out_dtype=np.float32,
                   grid=None, signature=None):
        """Get a launchable kernel.  ``signature`` (the CUDA C prototype in
        the reference) is accepted and ignored; shapes come from
        ``out_shape``/``launch``."""
        fn = self._fns.get(name)
        if fn is None:
            raise MXNetError("kernel %r not found (have %s)"
                             % (name, sorted(self._fns)))
        return PallasKernel(fn, name, out_shape, out_dtype, grid)


class PallasKernel:
    """One launchable Pallas kernel (the CudaKernel analog)."""

    def __init__(self, fn, name, out_shape=None, out_dtype=np.float32,
                 grid=None):
        self._fn = fn
        self.name = name
        self._out_shape = out_shape
        self._out_dtype = out_dtype
        self._grid = grid
        self._compiled = {}

    def launch(self, args: Sequence, ctx=None, grid=None, out_shape=None,
               out_dtype=None, interpret: Optional[bool] = None):
        """Run the kernel on NDArray inputs, returning an NDArray.

        Compiles per input-shape on first launch (the reference's per-device
        module load + launch, rtc.py CudaKernel.launch — grid/block become
        the Pallas ``grid``).
        """
        import jax
        from jax.experimental import pallas as pl
        from . import ndarray as nd

        arrays = [a._data if isinstance(a, nd.NDArray) else
                  jax.numpy.asarray(a) for a in args]
        oshape = out_shape or self._out_shape
        if oshape is None:
            if not arrays:
                raise MXNetError("PallasKernel.launch: out_shape is "
                                 "required for zero-argument kernels")
            oshape = arrays[0].shape
        oshape = tuple(oshape)
        odtype = np.dtype(out_dtype or self._out_dtype)
        g = grid if grid is not None else self._grid
        if g is not None and not isinstance(g, int):
            g = tuple(g)
        if interpret is None:
            # Mosaic compiles on TPU; everywhere else use interpreter mode
            interpret = jax.default_backend() not in ("tpu", "axon")
        key = (tuple((a.shape, str(a.dtype)) for a in arrays), oshape,
               str(odtype), g, interpret)
        call = self._compiled.get(key)
        if call is None:
            kw = {"out_shape": jax.ShapeDtypeStruct(oshape, odtype),
                  "interpret": interpret}
            if g is not None:
                kw["grid"] = g
            call = jax.jit(pl.pallas_call(self._fn, **kw))
            self._compiled[key] = call
        out = call(*arrays)
        octx = args[0]._ctx if args and isinstance(args[0], nd.NDArray) \
            else None
        return nd.NDArray(out, octx)


class CudaModule:
    """Reference-API stub: CUDA RTC does not exist on TPU."""

    def __init__(self, *a, **kw):
        raise MXNetError(
            "CudaModule (NVRTC) is a GPU feature; on TPU use "
            "mx.rtc.PallasModule with a Pallas kernel — same "
            "runtime-compilation workflow, Mosaic instead of NVRTC")
