"""NDArray utility helpers (parity: python/mxnet/ndarray/utils.py)."""
from __future__ import annotations

from .ndarray import NDArray, array, zeros as _zeros

__all__ = ["zeros_like_fn"]


def zeros_like_fn(arr):
    from .ndarray import invoke
    return invoke("zeros_like", [arr])
