"""``nd.contrib`` namespace: experimental/contrib operators.

Parity target: ``python/mxnet/ndarray/contrib.py`` (generated from the
``_contrib_`` op prefix, reference ndarray/register.py:142 convention).
"""
from __future__ import annotations

from ..ops.registry import OPS
from .register import _make_fn

_PREFIX = "_contrib_"


def populate(module_dict):
    for name in list(OPS):
        if name.startswith(_PREFIX):
            short = name[len(_PREFIX):]
            if short not in module_dict:
                module_dict[short] = _make_fn(name, display_name=short)


populate(globals())


def foreach(body, data, init_states):
    """Run a user body over axis 0 of ``data``, threading loop states
    (parity: python/mxnet/ndarray/contrib.py:101 / control_flow.cc:483).

    ``body(data_i, states) -> (outs, new_states)``.  Returns (stacked outs,
    final states).  Imperative form = the reference's per-step execution;
    the symbolic form (sym.contrib.foreach) lowers to ``lax.scan``.
    """
    from . import ndarray as _nd
    single_data = not isinstance(data, (list, tuple))
    single_state = not isinstance(init_states, (list, tuple))
    data_list = [data] if single_data else list(data)
    states = init_states if single_state else list(init_states)
    n = data_list[0].shape[0]
    collected = None
    single_out = False
    for i in range(n):
        xs = [d[i] for d in data_list]
        outs, states = body(xs[0] if single_data else xs, states)
        single_out = not isinstance(outs, (list, tuple))
        outs = [outs] if single_out else list(outs)
        if collected is None:
            collected = [[] for _ in outs]
        for slot, o in zip(collected, outs):
            slot.append(o)
    if collected is None:
        raise ValueError("foreach: empty data")
    stacked = [_nd.imperative_invoke("stack", *slot, axis=0, num_args=len(slot))
               for slot in collected]
    return (stacked[0] if single_out else stacked), states
