"""``nd.contrib`` namespace: experimental/contrib operators.

Parity target: ``python/mxnet/ndarray/contrib.py`` (generated from the
``_contrib_`` op prefix, reference ndarray/register.py:142 convention).
"""
from __future__ import annotations

from ..ops.registry import OPS
from .register import _make_fn

_PREFIX = "_contrib_"


def populate(module_dict):
    for name in list(OPS):
        if name.startswith(_PREFIX):
            short = name[len(_PREFIX):]
            if short not in module_dict:
                module_dict[short] = _make_fn(name, display_name=short)


populate(globals())
