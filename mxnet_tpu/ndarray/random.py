"""nd.random sampling namespace (parity: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .ndarray import imperative_invoke, NDArray, array as _array


def _pair(a, b):
    """Promote (NDArray, scalar) pairs for the _sample_* ops, which take
    per-element distribution params as arrays (reference requires both to be
    the same type; we accept mixed and broadcast the scalar)."""
    if not isinstance(b, NDArray):
        b = _array([float(b)] * a.size).reshape(a.shape)
    return a, b


def _call(op, shape=None, dtype=None, ctx=None, out=None, **params):
    kw = dict(params)
    if shape is not None:
        kw["shape"] = shape
    if dtype is not None:
        kw["dtype"] = dtype
    if ctx is not None:
        kw["ctx"] = ctx
    if out is not None:
        kw["out"] = out
    return imperative_invoke(op, **kw)


def uniform(low=0.0, high=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    if isinstance(low, NDArray):
        low, high = _pair(low, high)
        return imperative_invoke("_sample_uniform", low, high,
                                 shape=shape, dtype=dtype)
    return _call("_random_uniform", shape, dtype, ctx, out, low=low, high=high)


def normal(loc=0.0, scale=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    if isinstance(loc, NDArray):
        loc, scale = _pair(loc, scale)
        return imperative_invoke("_sample_normal", loc, scale,
                                 shape=shape, dtype=dtype)
    return _call("_random_normal", shape, dtype, ctx, out, loc=loc, scale=scale)


def randn(*shape, **kw):
    return normal(shape=shape or (1,), **kw)


def gamma(alpha=1.0, beta=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    if isinstance(alpha, NDArray):
        alpha, beta = _pair(alpha, beta)
        return imperative_invoke("_sample_gamma", alpha, beta,
                                 shape=shape, dtype=dtype)
    return _call("_random_gamma", shape, dtype, ctx, out,
                 alpha=alpha, beta=beta)


def exponential(lam=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    return _call("_random_exponential", shape, dtype, ctx, out, lam=lam)


def poisson(lam=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    return _call("_random_poisson", shape, dtype, ctx, out, lam=lam)


def negative_binomial(k=1, p=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    return _call("_random_negative_binomial", shape, dtype, ctx, out, k=k, p=p)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(), dtype=None,
                                  ctx=None, out=None, **kw):
    return _call("_random_generalized_negative_binomial", shape, dtype, ctx,
                 out, mu=mu, alpha=alpha)


def randint(low, high, shape=(), dtype=None, ctx=None, out=None, **kw):
    return _call("_random_randint", shape, dtype, ctx, out, low=low, high=high)


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    return imperative_invoke("_sample_multinomial", data, shape=shape,
                             get_prob=get_prob, dtype=dtype)


def shuffle(data, **kw):
    return imperative_invoke("_shuffle", data)
