"""Auto-generate ``nd.<op>`` functions from the operator registry.

Reference analog: ``python/mxnet/ndarray/register.py:142`` which code-gens
Python functions from C-API op introspection.  Here the registry is native
Python, so generation is a closure per op; every generated function accepts
positional NDArrays, keyword attrs, and ``out=``.
"""
from __future__ import annotations

import sys

from ..ops.registry import OPS
from .ndarray import imperative_invoke


def _make_fn(op_name, display_name=None):
    def fn(*args, **kwargs):
        return imperative_invoke(op_name, *args, **kwargs)
    fn.__name__ = display_name or op_name
    fn.__qualname__ = fn.__name__
    fn.__doc__ = OPS[op_name].doc
    return fn


def populate(module_dict, include_private=True):
    for name in list(OPS):
        if not include_private and name.startswith("_"):
            continue
        if name not in module_dict:
            module_dict[name] = _make_fn(name)
