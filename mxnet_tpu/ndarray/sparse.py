"""Sparse NDArrays: row_sparse and csr storage types.

Reference analog: ``include/mxnet/ndarray.h:61-66`` (``kDefaultStorage /
kRowSparseStorage / kCSRStorage``), ``python/mxnet/ndarray/sparse.py``
(1,633 LoC), sparse ops in ``src/operator/tensor/cast_storage-inl.h``,
``sparse_retain-inl.h``, ``dot-inl.h``.

TPU-native design (SURVEY.md §7.3 "Sparse"): XLA wants static shapes, so
dynamic-nnz bookkeeping (indices, indptr) lives on the HOST as numpy int64
arrays while the values ride the device as jax arrays.  Sparse-aware kernels
(dot, retain, elemwise add, lazy optimizer rows) are expressed as dense
gathers/scatters/segment-sums over the value block — static-shaped XLA
programs parameterized by the host-side index sets.  Any op without a
sparse-aware path falls back to densification, mirroring the reference's
storage-fallback dispatch (``FInferStorageType`` → ``kFComputeFallback``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "empty", "array",
           "cast_storage", "retain", "dot", "add"]


class BaseSparseNDArray(NDArray):
    """Common base: values on device, indices on host."""

    # NDArray declares __slots__; these extend the layout.  The parent's
    # `_data` slot stays unused — `_data` below shadows it with a property
    # that densifies on demand (the storage-fallback path).
    __slots__ = ("_sp_values", "_sp_indices", "_sp_indptr", "_sp_shape")

    def __init__(self, values, indices, indptr, shape, ctx=None):
        ctx = ctx or current_context()
        # bypass NDArray.__init__ (no dense buffer)
        self._ctx = ctx
        self._grad = None
        self._grad_req = "null"
        self._ag_leaf = False
        self._ag_entry = None
        self._sp_values = jnp.asarray(values)
        self._sp_indices = np.asarray(indices, dtype=np.int64)
        self._sp_indptr = None if indptr is None else \
            np.asarray(indptr, dtype=np.int64)
        self._sp_shape = tuple(int(s) for s in shape)

    # ---- identity ------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self._sp_shape

    @property
    def dtype(self):
        return np.dtype(self._sp_values.dtype.name)

    @property
    def ndim(self):
        return len(self._sp_shape)

    @property
    def data(self) -> NDArray:
        """The values array (reference: RowSparseNDArray.data / CSRNDArray.data)."""
        return NDArray(self._sp_values, self._ctx)

    @property
    def indices(self) -> NDArray:
        return NDArray(jnp.asarray(self._sp_indices), self._ctx)

    # ---- dense fallback ------------------------------------------------
    @property
    def _data(self):
        """Densify (storage-fallback dispatch): any dense-only op touching a
        sparse array transparently operates on its dense view."""
        return self._to_dense_jax()

    @_data.setter
    def _data(self, value):
        raise MXNetError("in-place dense writes are not supported on %s "
                         "(stype %r); use tostype('default') first"
                         % (type(self).__name__, self.stype))

    def _to_dense_jax(self):
        raise NotImplementedError

    def asnumpy(self):
        return np.asarray(self._to_dense_jax())

    def wait_to_read(self):
        self._sp_values.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def _replace(self, values, indices, indptr):
        if indptr is None:
            return type(self)(values, indices, self._sp_shape, self._ctx)
        return type(self)(values, indices, indptr, self._sp_shape, self._ctx)

    def astype(self, dtype):
        return self._replace(self._sp_values.astype(np.dtype(dtype)),
                             self._sp_indices, self._sp_indptr)

    def copy(self):
        return self._replace(jnp.array(self._sp_values),
                             self._sp_indices.copy(),
                             None if self._sp_indptr is None
                             else self._sp_indptr.copy())

    def copyto(self, other):
        if isinstance(other, Context):
            out = self.copy()
            out._ctx = other
            out._sp_values = jax.device_put(out._sp_values, other.jax_device)
            return out
        if isinstance(other, BaseSparseNDArray):
            if other.stype != self.stype:
                raise MXNetError("copyto: stype mismatch %s vs %s"
                                 % (self.stype, other.stype))
            other._sp_values = jnp.asarray(self._sp_values,
                                           other._sp_values.dtype)
            other._sp_indices = self._sp_indices.copy()
            other._sp_indptr = None if self._sp_indptr is None else \
                self._sp_indptr.copy()
            other._sp_shape = self._sp_shape
            return other
        # sparse → dense
        dense = self._to_dense_jax()
        NDArray.__dict__["_data"].__set__(
            other, jax.device_put(dense, other._ctx.jax_device)
            .astype(other.dtype))
        return other

    def as_in_context(self, ctx):
        return self if ctx == self._ctx else self.copyto(ctx)

    def tostype(self, stype: str):
        if stype == self.stype:
            return self
        if stype == "default":
            return NDArray(self._to_dense_jax(), self._ctx)
        return cast_storage(self, stype)

    def __repr__(self):
        return "<%s %s @%s, %d stored>" % (
            type(self).__name__, "x".join(map(str, self._sp_shape)),
            self._ctx, len(self._sp_indices))

    def __setitem__(self, key, value):
        raise MXNetError("__setitem__ is not supported on sparse NDArrays")

    def attach_grad(self, grad_req="write", stype=None):
        raise MXNetError("autograd on sparse leaves is not supported; "
                         "sparse gradients arrive via Embedding/dot "
                         "sparse_grad paths")


class RowSparseNDArray(BaseSparseNDArray):
    """Values for a subset of rows (reference ndarray.h kRowSparseStorage):
    ``dense[indices[i], ...] = values[i, ...]``, indices sorted unique."""

    def __init__(self, values, indices, shape, ctx=None):
        super().__init__(values, indices, None, shape, ctx)
        if self._sp_values.ndim != len(self._sp_shape):
            # values must be (nnz,) + shape[1:]
            raise MXNetError("row_sparse values ndim %d != %d"
                             % (self._sp_values.ndim, len(self._sp_shape)))

    @property
    def stype(self):
        return "row_sparse"

    def _to_dense_jax(self):
        out = jnp.zeros(self._sp_shape, self._sp_values.dtype)
        if len(self._sp_indices) == 0:
            return out
        return out.at[jnp.asarray(self._sp_indices)].set(self._sp_values)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def check_format(self, full_check=True):
        idx = self._sp_indices
        if len(idx) and (np.any(np.diff(idx) <= 0) or idx[0] < 0 or
                         idx[-1] >= self._sp_shape[0]):
            raise MXNetError("row_sparse indices must be sorted unique and "
                             "in range (ref: NDArray::CheckFormat)")
        if self._sp_values.shape[0] != len(idx):
            raise MXNetError("values/indices length mismatch")


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed sparse row (reference ndarray.h kCSRStorage)."""

    def __init__(self, values, indices, indptr, shape, ctx=None):
        super().__init__(values, indices, indptr, shape, ctx)
        if len(self._sp_shape) != 2:
            raise MXNetError("csr arrays are 2-D")

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return NDArray(jnp.asarray(self._sp_indptr), self._ctx)

    def _row_ids(self):
        """Per-nnz row id from indptr (host, static per array)."""
        counts = np.diff(self._sp_indptr)
        return np.repeat(np.arange(self._sp_shape[0]), counts)

    def _to_dense_jax(self):
        out = jnp.zeros(self._sp_shape, self._sp_values.dtype)
        if len(self._sp_indices) == 0:
            return out
        rows = jnp.asarray(self._row_ids())
        cols = jnp.asarray(self._sp_indices)
        return out.at[rows, cols].set(self._sp_values)

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, step = key.indices(self._sp_shape[0])
            if step != 1:
                raise MXNetError("csr slicing supports step 1 only")
            lo, hi = self._sp_indptr[start], self._sp_indptr[stop]
            return CSRNDArray(self._sp_values[int(lo):int(hi)],
                              self._sp_indices[lo:hi],
                              self._sp_indptr[start:stop + 1] - lo,
                              (stop - start, self._sp_shape[1]), self._ctx)
        raise MXNetError("csr indexing supports row slices only")

    def check_format(self, full_check=True):
        if len(self._sp_indptr) != self._sp_shape[0] + 1:
            raise MXNetError("indptr length must be rows+1")
        if np.any(np.diff(self._sp_indptr) < 0):
            raise MXNetError("indptr must be non-decreasing")
        if len(self._sp_indices) and (self._sp_indices.min() < 0 or
                                      self._sp_indices.max() >=
                                      self._sp_shape[1]):
            raise MXNetError("csr column indices out of range")


# --------------------------------------------------------------------------
# constructors (parity: python/mxnet/ndarray/sparse.py csr_matrix /
# row_sparse_array / zeros / empty / array)
# --------------------------------------------------------------------------
def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    dtype = np.dtype(dtype or np.float32)
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = _asnp(data).astype(dtype)
        if shape is None:
            raise MXNetError("csr_matrix((data, indices, indptr)) needs shape")
        return CSRNDArray(data, _asnp(indices), _asnp(indptr), shape, ctx)
    if isinstance(arg1, tuple) and len(arg1) == 2:
        return CSRNDArray(np.zeros((0,), dtype), np.zeros((0,), np.int64),
                          np.zeros(arg1[0] + 1, np.int64), arg1, ctx)
    dense = _asnp(arg1)
    return cast_storage(_dense_array(dense.astype(dtype), ctx), "csr")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    dtype = np.dtype(dtype or np.float32)
    if isinstance(arg1, tuple) and len(arg1) == 2 and not \
            isinstance(arg1[0], int):
        data, indices = arg1
        data = _asnp(data).astype(dtype)
        indices = _asnp(indices)
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) needs shape")
        order = np.argsort(indices)
        return RowSparseNDArray(data[order], indices[order], shape, ctx)
    if isinstance(arg1, tuple):  # shape tuple
        return RowSparseNDArray(
            np.zeros((0,) + tuple(arg1[1:]), dtype),
            np.zeros((0,), np.int64), arg1, ctx)
    dense = _asnp(arg1)
    return cast_storage(_dense_array(dense.astype(dtype), ctx), "row_sparse")


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = np.dtype(dtype or np.float32)
    if isinstance(shape, int):
        shape = (shape,)
    if stype == "row_sparse":
        return RowSparseNDArray(np.zeros((0,) + tuple(shape[1:]), dtype),
                                np.zeros((0,), np.int64), shape, ctx)
    if stype == "csr":
        return CSRNDArray(np.zeros((0,), dtype), np.zeros((0,), np.int64),
                          np.zeros(shape[0] + 1, np.int64), shape, ctx)
    if stype == "default":
        from .ndarray import zeros as _dz
        return _dz(shape, ctx, dtype)
    raise MXNetError("unknown stype %r" % stype)


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx, dtype)


def array(source, ctx=None, dtype=None):
    """mx.nd.sparse.array: build from scipy sparse / sparse NDArray."""
    if isinstance(source, BaseSparseNDArray):
        out = source.copy()
        if dtype is not None:
            out = out.astype(dtype)
        if ctx is not None:
            out = out.as_in_context(ctx)
        return out
    try:
        import scipy.sparse as sp
        if sp.issparse(source):
            csr = source.tocsr()
            return CSRNDArray(csr.data.astype(dtype or csr.dtype),
                              csr.indices, csr.indptr, csr.shape, ctx)
    except ImportError:
        pass
    raise MXNetError("sparse.array expects a sparse NDArray or scipy matrix")


def _asnp(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


# --------------------------------------------------------------------------
# sparse ops (reference: cast_storage, sparse_retain, dot FComputeEx)
# --------------------------------------------------------------------------
def cast_storage(arr, stype: str):
    """Convert between storage types (ref: tensor/cast_storage-inl.h).
    nnz discovery is host-side (dynamic shape); values stay device arrays."""
    if arr.stype == stype:
        return arr
    if stype == "default":
        return arr.tostype("default")
    dense = arr.asnumpy()
    if stype == "row_sparse":
        if dense.ndim < 1:
            raise MXNetError("row_sparse needs ndim >= 1")
        nz = np.where(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
        return RowSparseNDArray(jnp.asarray(dense[nz]), nz, dense.shape,
                                getattr(arr, "_ctx", None))
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr needs 2-D")
        rows, cols = np.nonzero(dense)
        indptr = np.zeros(dense.shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr)
        return CSRNDArray(jnp.asarray(dense[rows, cols]), cols, indptr,
                          dense.shape, getattr(arr, "_ctx", None))
    raise MXNetError("unknown stype %r" % stype)


def retain(rsp: RowSparseNDArray, row_ids):
    """Keep only rows whose index appears in row_ids
    (ref: tensor/sparse_retain-inl.h)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    ids = np.unique(_asnp(row_ids).astype(np.int64))
    mask = np.isin(rsp._sp_indices, ids)
    keep = np.where(mask)[0]
    return RowSparseNDArray(rsp._sp_values[jnp.asarray(keep)] if len(keep)
                            else np.zeros((0,) + rsp.shape[1:],
                                          rsp.dtype),
                            rsp._sp_indices[keep], rsp.shape, rsp._ctx)


import functools


@functools.partial(jax.jit, static_argnums=(4,))
def _csr_dot_jit(vals, rows, cols, B, n_rows):
    """out[i] = Σ_nnz(i) v * B[col] — jitted so the gather + segment-sum
    fuse into one executable (eager: ~700 ms for 82k nnz on CPU; jitted:
    ~0.02 ms — the nnz-proportional cost the reference's FComputeEx
    promises)."""
    contrib = vals[:, None] * B[cols]
    return jax.ops.segment_sum(contrib, rows, num_segments=n_rows)


@functools.partial(jax.jit, static_argnums=(4,))
def _csr_t_dot_jit(vals, rows, cols, B, n_cols):
    """out[j] = Σ v_ij * B[i] — scatter-add over column ids, jitted."""
    contrib = vals[:, None] * B[rows]
    return jnp.zeros((n_cols, B.shape[1]), contrib.dtype).at[cols].add(
        contrib)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (ref: tensor/dot-inl.h FComputeEx):
    csr · dense, csrᵀ · dense (returns dense), dense paths fall through."""
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray):
        if transpose_b:
            raise MXNetError("dot(csr, dense, transpose_b=True) unsupported")
        rows = jnp.asarray(lhs._row_ids())
        cols = jnp.asarray(lhs._sp_indices)
        vals = lhs._sp_values
        B = rhs._data
        if not transpose_a:
            out = _csr_dot_jit(vals, rows, cols, B, lhs.shape[0])
        else:
            out = _csr_t_dot_jit(vals, rows, cols, B, lhs.shape[1])
        return NDArray(out, rhs._ctx)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        # fallback: densify (reference logs a storage-fallback warning)
        from .ndarray import invoke
        return invoke("dot", [NDArray(lhs._data, getattr(lhs, "_ctx", None)),
                              NDArray(rhs._data, getattr(rhs, "_ctx", None))],
                      {"transpose_a": transpose_a,
                       "transpose_b": transpose_b})
    from .ndarray import invoke
    return invoke("dot", [lhs, rhs], {"transpose_a": transpose_a,
                                      "transpose_b": transpose_b})


def add(lhs: RowSparseNDArray, rhs: RowSparseNDArray) -> RowSparseNDArray:
    """row_sparse + row_sparse → row_sparse (union of rows, device add)."""
    if not (isinstance(lhs, RowSparseNDArray) and
            isinstance(rhs, RowSparseNDArray)):
        raise MXNetError("sparse.add expects two RowSparseNDArrays")
    if lhs.shape != rhs.shape:
        raise MXNetError("shape mismatch %s vs %s" % (lhs.shape, rhs.shape))
    union = np.union1d(lhs._sp_indices, rhs._sp_indices)
    n = len(union)
    out = jnp.zeros((n,) + lhs.shape[1:], lhs._sp_values.dtype)
    if len(lhs._sp_indices):
        li = jnp.asarray(np.searchsorted(union, lhs._sp_indices))
        out = out.at[li].add(lhs._sp_values)
    if len(rhs._sp_indices):
        ri = jnp.asarray(np.searchsorted(union, rhs._sp_indices))
        out = out.at[ri].add(rhs._sp_values)
    return RowSparseNDArray(out, union, lhs.shape, lhs._ctx)
