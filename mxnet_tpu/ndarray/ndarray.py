"""NDArray: the imperative tensor, backed by a jax.Array on TPU.

Reference analog: ``include/mxnet/ndarray.h:82-1001`` + ``src/ndarray/
ndarray.cc`` (async ref-counted chunk, engine-scheduled ops) and the Python
face ``python/mxnet/ndarray/ndarray.py``.

TPU-native design: the "chunk" is a ``jax.Array`` (PjRt buffer).  Asynchrony
is native — JAX dispatch is async and per-buffer ordering is maintained by the
runtime, so the reference's engine-var-per-chunk machinery maps onto PjRt
futures: ``wait_to_read`` = ``block_until_ready``.  Mutation (``x += y``,
``x[:] = v``, optimizer updates) swaps the underlying buffer — functionally
pure for XLA, in-place in API semantics.  Op dispatch goes through
:func:`invoke`, the analog of ``Imperative::Invoke`` →
``MXImperativeInvokeEx`` (``src/c_api/c_api_ndarray.cc:132``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, AttrDict, numeric_types, integer_types
from ..context import Context, current_context, cpu
from ..ops.registry import get_op, Operator
from .. import autograd as _autograd
from .. import random as _random

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "invoke", "concatenate", "save", "load", "imperative_invoke",
           "waitall", "moveaxis", "onehot_encode"]

class NDArray:
    """An imperative, mutable-by-buffer-swap tensor on a device."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_ag_leaf",
                 "_ag_entry", "__weakref__")

    def __init__(self, data: jax.Array, ctx: Optional[Context] = None):
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._grad_req = "null"
        self._ag_leaf = False
        self._ag_entry = None

    # ---- basic properties ----------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype.name if hasattr(self._data.dtype, "name")
                        else self._data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return invoke("transpose", [self])

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def dt_data(self):
        return self._data

    # ---- sync / host transfer ------------------------------------------
    def wait_to_read(self):
        """Block until pending writes complete (ref: NDArray::WaitToRead);
        re-raises async device errors here, matching the reference's
        exception-at-sync-point guarantee (SURVEY.md §5.2)."""
        self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            self.asnumpy(), "x".join(map(str, self.shape)), self._ctx)

    # ---- conversion -----------------------------------------------------
    def astype(self, dtype, copy=True) -> "NDArray":
        if not copy and np.dtype(dtype) == self.dtype:
            return self
        return NDArray(self._data.astype(np.dtype(dtype)), self._ctx)

    def copy(self) -> "NDArray":
        return NDArray(jnp.array(self._data), self._ctx)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        # device_put/astype return self._data UNCHANGED when device and
        # dtype already match — a genuine copy is required here, or the
        # "copy" aliases a buffer the fused step may later donate (and
        # XLA deletes donated buffers)
        if isinstance(other, Context):
            data = jax.device_put(self._data, other.jax_device)
            if data is self._data:
                data = jnp.array(data)
            return NDArray(data, other)
        data = jax.device_put(self._data, other._ctx.jax_device) \
            .astype(other._data.dtype)
        if data is self._data:
            data = jnp.array(data)
        other._data = data
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def as_in_ctx(self, ctx):
        return self.as_in_context(ctx)

    def detach(self) -> "NDArray":
        out = NDArray(self._data, self._ctx)
        return out

    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (ref: ndarray.py attach_grad →
        MarkVariables)."""
        grad = NDArray(jnp.zeros_like(self._data), self._ctx)
        _autograd.mark_variables([self], [grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _autograd.backward([self], [out_grad] if out_grad is not None else None,
                           retain_graph=retain_graph, train_mode=train_mode)

    # ---- shape ops (method forms) --------------------------------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if not shape and "shape" in kwargs:
            shape = tuple(kwargs["shape"])
        return invoke("Reshape", [self], {"shape": shape,
                                          "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other) -> "NDArray":
        return invoke("reshape_like", [self, other])

    def expand_dims(self, axis) -> "NDArray":
        return invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None) -> "NDArray":
        return invoke("squeeze", [self], {"axis": axis})

    def transpose(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], {"axes": axes})

    def swapaxes(self, dim1, dim2) -> "NDArray":
        return invoke("SwapAxis", [self], {"dim1": dim1, "dim2": dim2})

    def flatten(self) -> "NDArray":
        return invoke("Flatten", [self])

    def broadcast_to(self, shape) -> "NDArray":
        cur = (1,) * (len(shape) - self.ndim) + self.shape
        return invoke("broadcast_to", [self.reshape(cur)], {"shape": shape})

    def broadcast_like(self, other) -> "NDArray":
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        return invoke("tile", [self], {"reps": reps})

    def flip(self, axis):
        return invoke("reverse", [self], {"axis": axis})

    def sum(self, axis=None, keepdims=False, **kw):
        return invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def nansum(self, axis=None, keepdims=False, **kw):
        return invoke("nansum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False, **kw):
        return invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False, **kw):
        return invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False, **kw):
        return invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, **kw):
        return invoke("norm", [self], kw)

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, **kw):
        return invoke("argsort", [self], kw)

    def sort(self, **kw):
        return invoke("sort", [self], kw)

    def topk(self, **kw):
        return invoke("topk", [self], kw)

    def clip(self, a_min, a_max):
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return invoke("abs", [self])

    def sign(self):
        return invoke("sign", [self])

    def sqrt(self):
        return invoke("sqrt", [self])

    def square(self):
        return invoke("square", [self])

    def exp(self):
        return invoke("exp", [self])

    def log(self):
        return invoke("log", [self])

    def relu(self):
        return invoke("relu", [self])

    def sigmoid(self):
        return invoke("sigmoid", [self])

    def tanh(self):
        return invoke("tanh", [self])

    def softmax(self, *args, **kw):
        return invoke("softmax", [self], kw)

    def log_softmax(self, *args, **kw):
        return invoke("log_softmax", [self], kw)

    def round(self):
        return invoke("round", [self])

    def floor(self):
        return invoke("floor", [self])

    def ceil(self):
        return invoke("ceil", [self])

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self],
                      {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def pick(self, index, axis=-1, keepdims=False):
        return invoke("pick", [self, index],
                      {"axis": axis, "keepdims": keepdims})

    def one_hot(self, depth, **kw):
        return invoke("one_hot", [self], {"depth": depth, **kw})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("SliceChannel", [self],
                      {"num_outputs": num_outputs, "axis": axis,
                       "squeeze_axis": squeeze_axis})

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    def as_nd_ndarray(self):
        return self

    def asnumpy_or_none(self):
        return self.asnumpy()

    # ---- arithmetic dunders --------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return invoke(op, args)
        if isinstance(other, numeric_types):
            return invoke(scalar_op, [self], {"scalar": float(other)})
        return NotImplemented

    def __add__(self, other):
        return self._binary(other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        if isinstance(other, numeric_types):
            return invoke("_rminus_scalar", [self], {"scalar": float(other)})
        return self._binary(other, "broadcast_sub", None, reverse=True)

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        if isinstance(other, numeric_types):
            return invoke("_rdiv_scalar", [self], {"scalar": float(other)})
        return self._binary(other, "broadcast_div", None, reverse=True)

    def __mod__(self, other):
        return self._binary(other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        if isinstance(other, numeric_types):
            return invoke("_rmod_scalar", [self], {"scalar": float(other)})
        return self._binary(other, "broadcast_mod", None, reverse=True)

    def __pow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        if isinstance(other, numeric_types):
            return invoke("_rpower_scalar", [self], {"scalar": float(other)})
        return NotImplemented

    def __neg__(self):
        return invoke("negative", [self])

    def __abs__(self):
        return invoke("abs", [self])

    def __eq__(self, other):  # type: ignore[override]
        if other is None:
            return False
        return self._binary(other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):  # type: ignore[override]
        if other is None:
            return True
        return self._binary(other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return self._binary(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal",
                            "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal",
                            "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place forms: swap the underlying buffer
    def __iadd__(self, other):
        out = self.__add__(other)
        self._data = out._data.astype(self._data.dtype)
        return self

    def __isub__(self, other):
        out = self.__sub__(other)
        self._data = out._data.astype(self._data.dtype)
        return self

    def __imul__(self, other):
        out = self.__mul__(other)
        self._data = out._data.astype(self._data.dtype)
        return self

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._data = out._data.astype(self._data.dtype)
        return self

    # ---- indexing -------------------------------------------------------
    def _canon_index(self, key):
        if isinstance(key, NDArray):
            return key._data.astype(jnp.int32) if \
                np.issubdtype(key.dtype, np.floating) else key._data
        if isinstance(key, tuple):
            return tuple(self._canon_index(k) if isinstance(k, NDArray) else k
                         for k in key)
        return key

    def __getitem__(self, key):
        if isinstance(key, integer_types):
            return NDArray(self._data[int(key)], self._ctx)
        key = self._canon_index(key)
        return NDArray(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(value)
        dev = next(iter(self._data.devices()))
        if isinstance(key, slice) and key == slice(None):
            if isinstance(v, (int, float)):
                self._data = jnp.full_like(self._data, v)
            else:
                val = jnp.broadcast_to(jnp.asarray(v, self._data.dtype),
                                       self.shape)
                self._data = jax.device_put(val, dev)
            return
        key = self._canon_index(key)
        # cast to the array dtype (reference semantics: assignment casts)
        # and pin to this array's device (cross-device assignment copies)
        v = jax.device_put(jnp.asarray(v, self._data.dtype), dev)
        self._data = self._data.at[key].set(v)

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


# --------------------------------------------------------------------------
# the imperative dispatch — analog of Imperative::Invoke (imperative.cc:87)
# --------------------------------------------------------------------------
def invoke(op: Union[str, Operator], inputs: Sequence[NDArray],
           kwargs: Optional[Dict[str, Any]] = None,
           out: Optional[Union[NDArray, Sequence[NDArray]]] = None):
    """Execute one operator imperatively.

    Steps (mirroring the reference): parse attrs (param struct), pick
    compiled executable (cached per (op, attrs), shape-specialized by XLA),
    run async, optionally record on the autograd tape (RecordOp), apply
    aux/out writebacks.
    """
    if isinstance(op, str):
        op = get_op(op)
    kwargs = dict(kwargs or {})
    kwargs.pop("name", None)
    ctx = kwargs.pop("ctx", None)
    if out is None:
        out = kwargs.pop("out", None)
    else:
        kwargs.pop("out", None)
    # drop None-valued optional params so defaults apply
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    attrs = op.parse_attrs(kwargs)
    if op.train_aware:
        attrs = AttrDict({**attrs, "__train__": _autograd.is_training()})
    if op.nin == -1 and "num_args" in op.params:
        attrs = AttrDict({**attrs, "num_args": len(inputs)})

    arrays = []
    for a in inputs:
        if isinstance(a, NDArray):
            arrays.append(a._data)
        else:
            arrays.append(jnp.asarray(a))

    prefix = []
    if op.needs_rng:
        prefix = [_random.next_key()]

    recording = _autograd.is_recording() and any(
        _autograd._entry_of(a) is not None
        for a in inputs if isinstance(a, NDArray))

    from .. import profiler as _profiler
    _prof = _profiler.is_running()
    _pt0 = _profiler._now_us() if _prof else 0.0
    if recording:
        fn, _attrs, _prefix = op.fn, attrs, tuple(prefix)

        def pure(*xs):
            res = fn(_attrs, *_prefix, *xs)
            return res if isinstance(res, tuple) else (res,)

        outs, vjp_fn = jax.vjp(pure, *arrays)
        out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]

        def vjp_caller(cots, _v=vjp_fn, _av=out_avals):
            full = tuple(jnp.zeros(a.shape, a.dtype) if c is None else
                         jnp.asarray(c, a.dtype)
                         for c, a in zip(cots, _av))
            return _v(full)
    else:
        res = op.compiled(attrs)(*prefix, *arrays)
        outs = res if isinstance(res, tuple) else (res,)
        vjp_caller = None
    if _prof:
        # ProfileOperator analog (threaded_engine.h:80): span per dispatch
        _profiler.record_span(op.name, _pt0, _profiler._now_us())

    if ctx is not None and not isinstance(ctx, Context):
        ctx = Context(*ctx) if isinstance(ctx, tuple) else _parse_ctx(ctx)
    out_ctx = ctx or (inputs[0]._ctx if inputs and isinstance(inputs[0], NDArray)
                      else current_context())
    nd_outs = [NDArray(o, out_ctx) for o in outs]

    if recording:
        _autograd.record_op(op.name, vjp_caller,
                            [a for a in inputs if isinstance(a, NDArray)],
                            nd_outs)

    # aux writeback (BatchNorm moving stats, optimizer states)
    for oi, ii in op.get_aux_writeback(attrs).items():
        if ii < len(inputs) and isinstance(inputs[ii], NDArray):
            inputs[ii]._data = outs[oi]

    nvis = op.num_visible_outputs(attrs)
    nd_outs = nd_outs[:nvis]

    if out is not None:
        out_list = [out] if isinstance(out, NDArray) else list(out)
        for dst, src in zip(out_list, nd_outs):
            dst._data = src._data.astype(dst._data.dtype)
        return out if isinstance(out, NDArray) else out_list
    return nd_outs[0] if len(nd_outs) == 1 else nd_outs


def imperative_invoke(op_name, *args, **kwargs):
    """Generated-function entry (analog of _imperative_invoke,
    python/mxnet/_ctypes/ndarray.py:65).

    Positional NDArrays (or lists of them) are op inputs; positional
    scalars/tuples/strings fill the op's declared params in order —
    matching the generated-signature convention of the reference.
    """
    op = get_op(op_name)
    inputs = []
    scalars = []
    for a in args:
        if isinstance(a, NDArray):
            inputs.append(a)
        elif isinstance(a, (list, tuple)) and a and isinstance(a[0], NDArray):
            inputs.extend(a)
        elif isinstance(a, np.ndarray):
            inputs.append(array(a))
        elif isinstance(a, (int, float, str, tuple, list)):
            scalars.append(a)
        else:
            raise MXNetError("invalid positional argument %r to op %s"
                             % (type(a), op_name))
    # Array-valued keyword args are inputs placed by declared arg name
    # (reference generated signatures: F.LayerNorm(data, gamma=.., beta=..))
    kw_arrays = {}
    for k, v in kwargs.items():
        if k in ("out", "name", "ctx"):
            continue
        if isinstance(v, NDArray):
            kw_arrays[k] = v
        elif isinstance(v, np.ndarray):
            kw_arrays[k] = array(v)
    if kw_arrays:
        for k in kw_arrays:
            kwargs.pop(k)
        if op.arg_names:
            slots = {n: i for i, n in enumerate(op.arg_names)}
            hi = max((slots.get(k, -1) for k in kw_arrays), default=-1)
            ins = list(inputs) + [None] * max(0, hi + 1 - len(inputs))
            for k, v in kw_arrays.items():
                i = slots.get(k)
                if i is None:
                    ins.append(v)
                elif i < len(ins) and ins[i] is not None:
                    raise MXNetError(
                        "op %s: input %r given both positionally and by "
                        "keyword" % (op_name, k))
                else:
                    while len(ins) <= i:
                        ins.append(None)
                    ins[i] = v
            if any(v is None for v in ins):
                raise MXNetError(
                    "op %s: missing input(s) %s" % (op_name, [
                        op.arg_names[i] for i, v in enumerate(ins)
                        if v is None]))
            inputs = ins
        else:
            inputs.extend(kw_arrays.values())
    if scalars:
        for k in op.params:
            if not scalars:
                break
            if k in kwargs or k.startswith("__"):
                continue
            kwargs[k] = scalars.pop(0)
    return invoke(op, inputs, kwargs)


def _parse_ctx(s):
    if isinstance(s, Context):
        return s
    s = str(s)
    name, _, idx = s.partition("(")
    return Context(name.strip(), int(idx.rstrip(")")) if idx else 0)


# --------------------------------------------------------------------------
# creation
# --------------------------------------------------------------------------
def _put(value, ctx: Context):
    return jax.device_put(value, ctx.jax_device)


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(source, NDArray):
        data = source._data
        if dtype is not None:
            data = data.astype(np.dtype(dtype))
        return NDArray(_put(data, ctx), ctx)
    src = np.asarray(source)
    if dtype is None:
        dtype = np.float32 if src.dtype == np.float64 else src.dtype
    # The astype copy is load-bearing even for same-dtype sources:
    # device_put zero-copy-aliases suitably aligned host arrays on the CPU
    # backend, and nd.array must never alias caller memory (callers reuse
    # staging buffers — the universal MXNet pattern).
    return NDArray(_put(src.astype(np.dtype(dtype)), ctx), ctx)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_put(jnp.zeros(shape, np.dtype(dtype or "float32")), ctx), ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_put(jnp.ones(shape, np.dtype(dtype or "float32")), ctx), ctx)


def full(shape, val, ctx=None, dtype="float32", **kwargs) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_put(jnp.full(shape, val, np.dtype(dtype or "float32")), ctx), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    ctx = ctx or current_context()
    out = jnp.arange(start, stop, step, np.dtype(dtype or "float32"))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(_put(out, ctx), ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return invoke("Concat", list(arrays), {"dim": axis})


def moveaxis(tensor, source, destination):
    return NDArray(jnp.moveaxis(tensor._data, source, destination), tensor._ctx)


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = invoke("one_hot", [indices], {"depth": depth})
    out._data = res._data.astype(out._data.dtype)
    return out


def waitall():
    """Block until all async work completes (ref: MXNDArrayWaitAll)."""
    from .. import engine as _engine
    _engine.get().wait_for_all()
    (jax.effects_barrier if hasattr(jax, "effects_barrier") else lambda: None)()


# --------------------------------------------------------------------------
# save / load — reference format semantics (ndarray.cc Save/Load):
# a file holds either a list of arrays or a dict of name → array.
# Implementation: npz container (TPU build keeps the artifact semantics,
# SURVEY.md §5.4, not the binary layout).
# --------------------------------------------------------------------------
def _save_entries(prefix, a):
    """Flatten one array into npz entries; sparse arrays (reference
    ndarray.cc Save handles all three stypes) store their components."""
    from .sparse import RowSparseNDArray, CSRNDArray
    if isinstance(a, RowSparseNDArray):
        return {prefix + "/rsp_data": np.asarray(a._sp_values),
                prefix + "/rsp_indices": a._sp_indices,
                prefix + "/rsp_shape": np.asarray(a.shape, np.int64)}
    if isinstance(a, CSRNDArray):
        return {prefix + "/csr_data": np.asarray(a._sp_values),
                prefix + "/csr_indices": a._sp_indices,
                prefix + "/csr_indptr": a._sp_indptr,
                prefix + "/csr_shape": np.asarray(a.shape, np.int64)}
    return {prefix: a.asnumpy()}


def _load_entry(z, prefix):
    from .sparse import RowSparseNDArray, CSRNDArray
    if prefix + "/rsp_data" in z:
        return RowSparseNDArray(z[prefix + "/rsp_data"],
                                z[prefix + "/rsp_indices"],
                                tuple(z[prefix + "/rsp_shape"]))
    if prefix + "/csr_data" in z:
        return CSRNDArray(z[prefix + "/csr_data"],
                          z[prefix + "/csr_indices"],
                          z[prefix + "/csr_indptr"],
                          tuple(z[prefix + "/csr_shape"]))
    return array(z[prefix])


def save(fname: str, data):
    entries = {}
    if isinstance(data, NDArray):
        entries.update(_save_entries("arr:0", data))
    elif isinstance(data, (list, tuple)):
        for i, a in enumerate(data):
            entries.update(_save_entries("arr:%d" % i, a))
    elif isinstance(data, dict):
        for k, v in data.items():
            entries.update(_save_entries("name:" + k, v))
    else:
        raise MXNetError("save expects NDArray, list, or dict")
    np.savez(_norm(fname), **entries)


_SPARSE_SUFFIXES = ("/rsp_data", "/rsp_indices", "/rsp_shape",
                    "/csr_data", "/csr_indices", "/csr_indptr", "/csr_shape")


def load(fname: str):
    with np.load(_norm(fname), allow_pickle=False) as z:
        prefixes = []
        for k in z.keys():
            p = k
            for suf in _SPARSE_SUFFIXES:
                if k.endswith(suf):
                    p = k[:-len(suf)]
                    break
            if p not in prefixes:
                prefixes.append(p)
        if all(p.startswith("arr:") for p in prefixes):
            items = sorted(prefixes, key=lambda k: int(k.split(":")[1]))
            return [_load_entry(z, p) for p in items]
        return {p.split(":", 1)[1]: _load_entry(z, p) for p in prefixes}


def _norm(fname):
    if not isinstance(fname, str):
        return fname  # file-like object (predictor bytes-params path)
    return fname if fname.endswith(".npz") else fname + ".npz"
