"""``nd.image`` namespace (parity: python/mxnet/ndarray/image.py, generated
from the ``_image_`` op prefix)."""
from __future__ import annotations

from ..ops.registry import OPS
from .register import _make_fn

_PREFIX = "_image_"

for _name in list(OPS):
    if _name.startswith(_PREFIX):
        _short = _name[len(_PREFIX):]
        globals()[_short] = _make_fn(_name, display_name=_short)
