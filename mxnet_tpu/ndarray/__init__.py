"""NDArray package: imperative arrays + generated op namespace.

Parity target: ``python/mxnet/ndarray/`` (ndarray.py, generated gen_*,
sparse.py, random.py).
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      invoke, concatenate, save, load, imperative_invoke,
                      waitall, moveaxis, onehot_encode)
from . import register as _register
from . import random  # noqa: F401
from . import sparse  # noqa: F401
from . import contrib  # noqa: F401
from . import image  # noqa: F401
from .sparse import (BaseSparseNDArray, RowSparseNDArray, CSRNDArray,
                     cast_storage)

_register.populate(globals())

from .utils import *  # noqa: F401,F403


def sparse_retain(data, indices):
    """Retain rows of a row_sparse array (or mask rows of a dense one).

    Parity: ``mx.nd.sparse_retain`` (ref: src/operator/tensor/
    sparse_retain.cc:27).  RowSparseNDArray input stays row_sparse; dense
    input goes through the registered XLA op (rows not in ``indices``
    zeroed).
    """
    if isinstance(data, RowSparseNDArray):
        return sparse.retain(data, indices)
    return imperative_invoke("sparse_retain", data, indices)


def maximum(lhs, rhs):
    """mx.nd.maximum with scalar/array dispatch (parity: ndarray.py)."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return imperative_invoke("broadcast_maximum", lhs, rhs)
    if isinstance(lhs, NDArray):
        return imperative_invoke("_maximum_scalar", lhs, scalar=float(rhs))
    if isinstance(rhs, NDArray):
        return imperative_invoke("_maximum_scalar", rhs, scalar=float(lhs))
    return max(lhs, rhs)


def minimum(lhs, rhs):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return imperative_invoke("broadcast_minimum", lhs, rhs)
    if isinstance(lhs, NDArray):
        return imperative_invoke("_minimum_scalar", lhs, scalar=float(rhs))
    if isinstance(rhs, NDArray):
        return imperative_invoke("_minimum_scalar", rhs, scalar=float(lhs))
    return min(lhs, rhs)
