"""NDArray package: imperative arrays + generated op namespace.

Parity target: ``python/mxnet/ndarray/`` (ndarray.py, generated gen_*,
sparse.py, random.py).
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      invoke, concatenate, save, load, imperative_invoke,
                      waitall, moveaxis, onehot_encode)
from . import register as _register
from . import random  # noqa: F401

_register.populate(globals())

from .utils import *  # noqa: F401,F403
