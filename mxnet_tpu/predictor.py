"""Inference-only predict API.

Reference analog: ``include/mxnet/c_predict_api.h:78-200`` +
``src/c_api/c_predict_api.cc`` (SURVEY.md N18): create a predictor from a
symbol JSON + a parameter blob + input shapes, then
``set_input → forward → get_output`` — the minimal embedding surface used by
the amalgamation/mobile builds.

TPU-native: the bound graph compiles to ONE fused XLA inference program per
input shape (the ``MXNET_PREDICT_ONLY`` engine fallback becomes simply "no
gradient graph").
"""
from __future__ import annotations

import io
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError

__all__ = ["Predictor", "load_ndarray_file"]


def load_ndarray_file(nd_bytes: bytes):
    """Load a parameter blob (the MXNDArray save format) into a dict
    (parity: MXNDListCreate in c_predict_api)."""
    from . import ndarray as nd
    bio = io.BytesIO(nd_bytes)
    return nd.load(bio)


class Predictor:
    """Forward-only executor (parity: MXPredCreate family).

    Parameters
    ----------
    symbol_json : str
        Symbol JSON (the string itself or a path ending in .json).
    params : bytes | dict | str
        Parameter blob bytes (save format), a {name: NDArray} dict (with
        optional ``arg:``/``aux:`` name prefixes, checkpoint convention),
        or a path to a .params file.
    ctx : Context, optional
    input_shapes : dict of name -> shape
    """

    def __init__(self, symbol_json, params, ctx=None, input_shapes=None,
                 dev_type=None, dev_id=0):
        from . import context as _ctx_mod
        from . import ndarray as nd
        from . import symbol as sym_mod

        if dev_type is not None:
            ctx = _ctx_mod.Context(dev_type, dev_id)
        self._ctx = ctx or _ctx_mod.current_context()

        if isinstance(symbol_json, str) and symbol_json.endswith(".json"):
            with open(symbol_json) as f:
                symbol_json = f.read()
        self._symbol = sym_mod.load_json(symbol_json)

        if isinstance(params, (bytes, bytearray)):
            loaded = load_ndarray_file(bytes(params))
        elif isinstance(params, str):
            loaded = nd.load(params)
        else:
            loaded = dict(params)
        self._arg_params = {}
        self._aux_params = {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                self._arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                self._aux_params[k[4:]] = v
            else:
                self._arg_params[k] = v
        self._bind(input_shapes)

    def _bind(self, input_shapes):
        """Bind the (already parsed) symbol + params for these shapes."""
        from . import ndarray as nd

        input_shapes = dict(input_shapes or {})
        if not input_shapes:
            raise MXNetError("Predictor needs input_shapes (e.g. "
                             "{'data': (1, 3, 224, 224)})")
        self._input_names = list(input_shapes)
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(input_shapes[name], ctx=self._ctx)
            elif name in self._arg_params:
                args[name] = self._arg_params[name].as_in_context(self._ctx)
            else:
                raise MXNetError("missing parameter %r" % name)
        auxs = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name in self._aux_params:
                auxs[name] = self._aux_params[name].as_in_context(self._ctx)
            else:
                # zero-filling e.g. BatchNorm moving_var would silently
                # produce garbage inference — fail like the arg path does
                raise MXNetError("missing auxiliary state %r" % name)
        self._executor = self._symbol.bind(self._ctx, args, grad_req="null",
                                           aux_states=auxs)
        self._outputs = None

    # ---- the C predict API surface ---------------------------------------
    def set_input(self, name, value):
        """MXPredSetInput.

        NDArray values already on device are adopted directly (an
        identity ``astype`` when dtypes match — zero copies); everything
        else takes the host-upload path.  The old behaviour round-tripped
        device arrays through ``asnumpy()`` — a device→host→device bounce
        per request, fatal for a serving hot path.
        """
        if name not in self._input_names:
            raise MXNetError("unknown input %r (have %s)"
                             % (name, self._input_names))
        dst = self._executor.arg_dict[name]
        data = getattr(value, "_data", None)
        if data is not None:                   # NDArray: stay on device
            if tuple(data.shape) != dst.shape:
                raise MXNetError("input %r has shape %s, bound shape is %s"
                                 % (name, tuple(data.shape), dst.shape))
            dst._data = data.astype(dst.dtype)
        else:
            dst[:] = np.asarray(value)

    def forward(self, **inputs):
        """MXPredForward; keyword inputs are a convenience for set_input."""
        for k, v in inputs.items():
            self.set_input(k, v)
        self._outputs = self._executor.forward(is_train=False)
        return self._outputs

    def get_output(self, index=0):
        """MXPredGetOutput."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index]

    def reshape(self, input_shapes):
        """MXPredReshape: rebind for new input shapes.

        Shares this predictor's symbol and parameter objects — no
        ``tojson()``/re-parse round trip, no parameter copies; only the
        bind (and XLA's per-shape compile on first forward) is new.  This
        is what makes a per-bucket predictor set cheap for the serving
        layer.
        """
        new = Predictor.__new__(Predictor)
        new._ctx = self._ctx
        new._symbol = self._symbol
        new._arg_params = self._arg_params
        new._aux_params = self._aux_params
        new._bind(input_shapes)
        return new
