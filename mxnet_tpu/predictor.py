"""Inference-only predict API.

Reference analog: ``include/mxnet/c_predict_api.h:78-200`` +
``src/c_api/c_predict_api.cc`` (SURVEY.md N18): create a predictor from a
symbol JSON + a parameter blob + input shapes, then
``set_input → forward → get_output`` — the minimal embedding surface used by
the amalgamation/mobile builds.

TPU-native: the bound graph compiles to ONE fused XLA inference program per
input shape (the ``MXNET_PREDICT_ONLY`` engine fallback becomes simply "no
gradient graph").

Mesh-sharded inference: pass ``mesh=`` (a ``jax.sharding.Mesh``) and
optionally ``sharding_rules=`` (a
:class:`~mxnet_tpu.parallel.mesh.ShardingRules`; defaults to
``megatron_rules`` when the mesh has a ``tp`` axis) and one large model
spans every device in the mesh: parameters are ``device_put`` with their
rule's ``NamedSharding``, inputs are replicated, and GSPMD partitions the
single forward program — column-parallel FCs shard activations, row-
parallel FCs insert the all-reduce, exactly the
``parallel/tensor_parallel.py`` math without hand-written collectives.
The mesh signature joins the executor's program cache key (PR 6 / GL001
contract), so a (model, bucket, mesh) triple is one program.
"""
from __future__ import annotations

import io
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError

__all__ = ["Predictor", "load_ndarray_file"]


def load_ndarray_file(nd_bytes: bytes):
    """Load a parameter blob (the MXNDArray save format) into a dict
    (parity: MXNDListCreate in c_predict_api)."""
    from . import ndarray as nd
    bio = io.BytesIO(nd_bytes)
    return nd.load(bio)


class Predictor:
    """Forward-only executor (parity: MXPredCreate family).

    Parameters
    ----------
    symbol_json : str
        Symbol JSON (the string itself or a path ending in .json).
    params : bytes | dict | str
        Parameter blob bytes (save format), a {name: NDArray} dict (with
        optional ``arg:``/``aux:`` name prefixes, checkpoint convention),
        or a path to a .params file.
    ctx : Context, optional
    input_shapes : dict of name -> shape
    mesh : jax.sharding.Mesh, optional
        Shard this predictor across the mesh (GSPMD tensor parallel).
    sharding_rules : ShardingRules, optional
        Parameter-name → PartitionSpec rules; defaults to
        ``megatron_rules(mesh)`` when the mesh has a ``tp`` axis, else
        fully replicated.
    """

    def __init__(self, symbol_json, params, ctx=None, input_shapes=None,
                 dev_type=None, dev_id=0, mesh=None, sharding_rules=None):
        from . import context as _ctx_mod
        from . import ndarray as nd
        from . import symbol as sym_mod

        if dev_type is not None:
            ctx = _ctx_mod.Context(dev_type, dev_id)
        self._ctx = ctx or _ctx_mod.current_context()
        self._mesh = mesh
        self._rules = self._default_rules(mesh, sharding_rules)

        if isinstance(symbol_json, str) and symbol_json.endswith(".json"):
            with open(symbol_json) as f:
                symbol_json = f.read()
        self._symbol = sym_mod.load_json(symbol_json)

        if isinstance(params, (bytes, bytearray)):
            loaded = load_ndarray_file(bytes(params))
        elif isinstance(params, str):
            loaded = nd.load(params)
        else:
            loaded = dict(params)
        self._arg_params = {}
        self._aux_params = {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                self._arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                self._aux_params[k[4:]] = v
            else:
                self._arg_params[k] = v
        self._bind(input_shapes)

    @staticmethod
    def _default_rules(mesh, sharding_rules):
        if mesh is None or sharding_rules is not None:
            return sharding_rules
        from .parallel.mesh import ShardingRules, megatron_rules
        if "tp" in mesh.shape:
            return megatron_rules(mesh)
        return ShardingRules(mesh)

    def _bind(self, input_shapes):
        """Bind the (already parsed) symbol + params for these shapes."""
        from . import ndarray as nd

        input_shapes = dict(input_shapes or {})
        if not input_shapes:
            raise MXNetError("Predictor needs input_shapes (e.g. "
                             "{'data': (1, 3, 224, 224)})")
        self._input_names = list(input_shapes)
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**input_shapes)
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(input_shapes[name], ctx=self._ctx)
            elif name in self._arg_params:
                args[name] = self._arg_params[name].as_in_context(self._ctx)
            else:
                raise MXNetError("missing parameter %r" % name)
        auxs = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name in self._aux_params:
                auxs[name] = self._aux_params[name].as_in_context(self._ctx)
            else:
                # zero-filling e.g. BatchNorm moving_var would silently
                # produce garbage inference — fail like the arg path does
                raise MXNetError("missing auxiliary state %r" % name)
        if self._mesh is not None:
            self._shard_bindings(args, auxs, input_shapes)
        self._executor = self._symbol.bind(self._ctx, args, grad_req="null",
                                           aux_states=auxs)
        if self._mesh is not None:
            self._executor._mesh_sig = self._mesh_sig
        self._outputs = None

    def _shard_bindings(self, args, auxs, input_shapes):
        """Place every bound array on the mesh: params per the sharding
        rules, inputs (and aux state) replicated.  Fresh NDArray wrappers
        — the shared ``_arg_params`` objects are never mutated, so a
        single-chip predictor over the same params stays untouched.
        Also derives ``_mesh_sig``: (mesh axes/sizes, per-array spec) —
        everything that selects the partitioned program."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from .ndarray.ndarray import NDArray

        replicated = NamedSharding(self._mesh, PartitionSpec())
        self._replicated = replicated
        specs = []
        for pool in (args, auxs):
            for name, arr in pool.items():
                if name in input_shapes or pool is auxs:
                    sh = replicated
                else:
                    sh = self._rules.sharding_for(name, arr.shape)
                placed = jax.device_put(arr._data, sh)
                pool[name] = NDArray(placed, self._ctx)
                specs.append((name, str(sh.spec)))
        self._mesh_sig = (
            tuple(sorted((str(a), int(s))
                         for a, s in self._mesh.shape.items())),
            tuple(sorted(specs)))

    # ---- the C predict API surface ---------------------------------------
    def set_input(self, name, value):
        """MXPredSetInput.

        NDArray values already on device are adopted directly (an
        identity ``astype`` when dtypes match — zero copies); everything
        else takes the host-upload path.  The old behaviour round-tripped
        device arrays through ``asnumpy()`` — a device→host→device bounce
        per request, fatal for a serving hot path.
        """
        if name not in self._input_names:
            raise MXNetError("unknown input %r (have %s)"
                             % (name, self._input_names))
        dst = self._executor.arg_dict[name]
        data = getattr(value, "_data", None)
        if self._mesh is not None:
            # replicate the input across the mesh: GSPMD needs every
            # operand of the partitioned program to carry a mesh sharding
            # (mixing a single-device committed array with sharded params
            # is an error, and an uncommitted one would recompile)
            import jax
            arr = data if data is not None \
                else np.asarray(value, dtype=dst.dtype)
            if tuple(arr.shape) != dst.shape:
                raise MXNetError("input %r has shape %s, bound shape is %s"
                                 % (name, tuple(arr.shape), dst.shape))
            arr = jax.device_put(arr, self._replicated)
            dst._data = arr if arr.dtype == dst.dtype \
                else arr.astype(dst.dtype)
        elif data is not None:                 # NDArray: stay on device
            if tuple(data.shape) != dst.shape:
                raise MXNetError("input %r has shape %s, bound shape is %s"
                                 % (name, tuple(data.shape), dst.shape))
            dst._data = data.astype(dst.dtype)
        else:
            dst[:] = np.asarray(value)

    def forward(self, **inputs):
        """MXPredForward; keyword inputs are a convenience for set_input."""
        for k, v in inputs.items():
            self.set_input(k, v)
        self._outputs = self._executor.forward(is_train=False)
        return self._outputs

    def get_output(self, index=0):
        """MXPredGetOutput."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index]

    def reshape(self, input_shapes):
        """MXPredReshape: rebind for new input shapes.

        Shares this predictor's symbol and parameter objects — no
        ``tojson()``/re-parse round trip, no parameter copies; only the
        bind (and XLA's per-shape compile on first forward) is new.  This
        is what makes a per-bucket predictor set cheap for the serving
        layer.
        """
        new = Predictor.__new__(Predictor)
        new._ctx = self._ctx
        new._symbol = self._symbol
        new._arg_params = self._arg_params
        new._aux_params = self._aux_params
        new._mesh = self._mesh
        new._rules = self._rules
        new._bind(input_shapes)
        return new

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=True):
        """Copy new weights into the bound executor (hot-swap path).

        On a mesh predictor the copied values are re-``device_put`` to
        each parameter's rule sharding afterwards — a plain elementwise
        write would leave the array on GSPMD's choice of layout, and a
        layout change would silently recompile the forward program on
        the next request (exactly what the serving post-warmup-compile
        contract forbids)."""
        self._executor.copy_params_from(arg_params, aux_params,
                                        allow_extra_params)
        if self._mesh is not None:
            import jax
            for name, arr in self._executor.arg_dict.items():
                if name in self._input_names:
                    continue
                sh = self._rules.sharding_for(name, arr.shape)
                arr._data = jax.device_put(arr._data, sh)
            for arr in self._executor.aux_dict.values():
                arr._data = jax.device_put(arr._data, self._replicated)
        # hot-swap memory hygiene: re-point the shared param dicts at the
        # live bound arrays.  The construction-time copies (mesh
        # predictors and cross-context binds hold distinct buffers) would
        # otherwise pin a dead weight generation in HBM across every
        # future swap; after this, dropping the swap source releases it.
        for name, arr in self._executor.arg_dict.items():
            if name not in self._input_names and name in self._arg_params:
                self._arg_params[name] = arr
        for name, arr in self._executor.aux_dict.items():
            if name in self._aux_params:
                self._aux_params[name] = arr
