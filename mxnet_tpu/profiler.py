"""Profiler (parity: ``python/mxnet/profiler.py`` over SURVEY.md N16/§5.1).

Reference analog: ``src/profiler/profiler.{h,cc}`` + ``c_api_profile.cc`` —
Chrome-trace JSON of per-op spans recorded by the engine
(``ProfileOperator`` wraps each executed op, threaded_engine.h:80), an
in-memory aggregate table (``aggregate_stats.cc``), and user-defined
Domain/Task/Frame/Event/Counter/Marker objects.

TPU-native design: the host-side dispatch layer (imperative ``invoke`` and
the Executor) is where op spans are recorded — device-side XLA timing comes
from ``jax.profiler`` (start/stop a TensorBoard trace alongside when
``profile_device`` is requested), keeping the reference's "profile
everything through the scheduler" shape with XLA as the device half.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from . import telemetry as _telemetry
from .base import get_env

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "profiler_set_config", "profiler_set_state",
           "Domain", "Task", "Frame", "Event", "Counter", "Marker"]

# user-defined profiler counters mirrored into the telemetry registry so a
# /metrics scrape sees the same values a Chrome trace would
_PROF_GAUGE = _telemetry.gauge(
    "profiler_counter", "Latest value of each profiler.Counter",
    ("domain", "counter"))

# the in-memory event list is capped (long runs used to grow it until OOM);
# drops are counted unconditionally — losing trace data is an error signal
_DROPPED = _telemetry.counter(
    "profiler_events_dropped_total",
    "Profiler events dropped by the in-memory cap (MXNET_PROFILER_MAX_EVENTS)")

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "profile_device": False,
    "tensorboard_dir": None,
}
_state = "stop"          # 'run' | 'stop'
_paused = False
_events: List[dict] = []
_max_events = get_env("MXNET_PROFILER_MAX_EVENTS", 1_000_000, int)
_t0 = time.perf_counter()
_jax_trace_active = False

# set by mxnet_tpu.tracing at import: its FlightRecorder, fed every span that
# goes through record_span even when the profiler is stopped
_flight = None


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def is_running():
    return _state == "run" and not _paused


def _append_event(ev: dict):
    """Capped append shared by spans, counters, markers and flow events."""
    with _lock:
        if len(_events) >= _max_events:
            _DROPPED.inc()
            return
        _events.append(ev)


def record_span(name: str, begin_us: float, end_us: float,
                category: str = "operator", args: Optional[dict] = None):
    """Append one complete span (the ProfileOperator analog).

    Also feeds the flight-recorder ring (tracing.flight) when that is on —
    the ring stays warm even with the profiler stopped, so a post-mortem
    dump has the last N spans regardless of collection state."""
    fl = _flight
    if fl is not None and fl.enabled:
        fl.record(name, category, begin_us, end_us, args)
    if not is_running():
        return
    ev = {"name": name, "cat": category, "ph": "X",
          "ts": begin_us, "dur": end_us - begin_us,
          "pid": os.getpid(),
          "tid": threading.get_ident() % 100000}
    if args:
        ev["args"] = args
    _append_event(ev)


class span:
    """Context manager used by the dispatch layer around each op.

    ``histogram`` (a telemetry Histogram or bound child) receives the same
    wall-clock measurement in seconds when telemetry is enabled, so one
    timing path feeds both the Chrome trace and the metrics registry."""

    __slots__ = ("name", "cat", "begin", "hist", "args")

    def __init__(self, name, category="operator", histogram=None, args=None):
        self.name = name
        self.cat = category
        self.hist = histogram
        self.args = args

    def __enter__(self):
        self.begin = _now_us()
        return self

    def __exit__(self, *exc):
        end = _now_us()
        record_span(self.name, self.begin, end, self.cat, args=self.args)
        if self.hist is not None and _telemetry.enabled:
            self.hist.observe((end - self.begin) * 1e-6)
        return False


def set_config(**kwargs):
    """Configure the profiler (parity: profiler.py:28 set_config)."""
    for k, v in kwargs.items():
        if k not in _config:
            # tolerate reference-only knobs silently (e.g. continuous_dump)
            continue
        _config[k] = v


profiler_set_config = set_config  # legacy alias (reference keeps both)


def set_state(state="stop"):
    """'run' starts collection; 'stop' ends it (parity: set_state)."""
    global _state, _jax_trace_active
    if state not in ("run", "stop"):
        raise ValueError("profiler state must be 'run' or 'stop'")
    if state == "run" and _state != "run":
        if _config["profile_device"] and _config["tensorboard_dir"]:
            import jax
            jax.profiler.start_trace(_config["tensorboard_dir"])
            _jax_trace_active = True
    if state == "stop" and _state == "run" and _jax_trace_active:
        import jax
        jax.profiler.stop_trace()
        _jax_trace_active = False
    _state = state


profiler_set_state = set_state


def pause():
    global _paused
    _paused = True


def resume():
    global _paused
    _paused = False


def dump(finished=True, filename=None):
    """Write the Chrome-trace JSON file (parity: Profiler::DumpProfile).

    ``finished=False`` keeps the event buffer intact (mid-run snapshot);
    only ``finished=True`` clears it.  The write is atomic (temp file +
    rename) so a crash mid-dump can never leave a truncated trace.  The
    ``metadata`` block carries what ``tools/merge_traces.py`` needs to
    clock-align and label per-process traces from a dist run."""
    with _lock:
        events = list(_events)
        if finished:
            _events.clear()
    path = filename or _config["filename"]
    meta = {
        # unix epoch (us) of this process's ts origin: merge_traces.py uses
        # the per-file difference to shift events onto one clock
        "t0_unix_us": time.time() * 1e6 - _now_us(),
        "pid": os.getpid(),
        "rank": int(os.environ.get("DMLC_WORKER_ID", "0") or 0),
        "role": os.environ.get("DMLC_ROLE", "worker"),
    }
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "us",
                   "metadata": meta}, f)
    os.replace(tmp, path)
    return path


def dumps(reset=False):
    """Aggregate-stats table as a string
    (parity: MXAggregateProfileStatsPrint)."""
    with _lock:
        events = list(_events)
        if reset:
            _events.clear()
    agg: Dict[str, List[float]] = defaultdict(list)
    for e in events:
        if "dur" in e:  # complete spans only (not counters/markers)
            agg[e["name"]].append(e["dur"])
    lines = ["%-40s %8s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for name in sorted(agg, key=lambda n: -sum(agg[n])):
        durs = agg[name]
        lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f" %
                     (name, len(durs), sum(durs), min(durs), max(durs),
                      sum(durs) / len(durs)))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# user-defined profiling objects (parity: profiler.py Domain/Task/Frame/...)
# ---------------------------------------------------------------------------
class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    def __init__(self, domain, name, category):
        self.domain = domain
        self.name = name
        self._cat = category
        self._begin = None

    def start(self):
        self._begin = _now_us()

    def stop(self):
        if self._begin is not None:
            record_span("%s::%s" % (self.domain.name, self.name),
                        self._begin, _now_us(), self._cat)
            self._begin = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_Span):
    def __init__(self, domain, name):
        super().__init__(domain, name, "task")


class Frame(_Span):
    def __init__(self, domain, name):
        super().__init__(domain, name, "frame")


class Event(_Span):
    def __init__(self, name):
        super().__init__(Domain("event"), name, "event")


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if _telemetry.enabled:
            _PROF_GAUGE.labels(domain=self.domain.name,
                               counter=self.name).set(value)
        if is_running():
            _append_event({"name": "%s::%s" % (self.domain.name, self.name),
                           "cat": "counter", "ph": "C",
                           "ts": _now_us(), "pid": os.getpid(),
                           "args": {"value": value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if is_running():
            _append_event({"name": "%s::%s" % (self.domain.name, self.name),
                           "cat": "marker", "ph": "i", "ts": _now_us(),
                           "pid": os.getpid(), "s": scope[0]})
