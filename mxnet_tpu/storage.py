"""Storage inspection (parity shim for SURVEY.md N2).

Reference analog: ``include/mxnet/storage.h`` + ``src/storage/
pooled_storage_manager.h`` — per-device memory pools with env-tunable
reserve/page knobs.  On TPU, device memory is owned by PjRt/XLA (its own
HBM pooling), so the *management* half has no user surface; what remains
useful is the *inspection* half: per-device usage stats for the profiler
and OOM debugging.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax

__all__ = ["memory_stats", "bytes_allocated", "bytes_limit", "report"]


def memory_stats(device: Optional[object] = None) -> Dict:
    """Raw allocator stats of a device (PjRt ``memory_stats``); {} when the
    backend doesn't expose them (e.g. CPU)."""
    dev = device or jax.devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except (AttributeError, jax.errors.JaxRuntimeError):
        return {}


def bytes_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def bytes_limit(device=None) -> int:
    return int(memory_stats(device).get("bytes_limit", 0))


def report() -> str:
    """Human-readable per-device memory table (the
    ``MXAggregateProfileStatsPrint`` memory-section analog)."""
    lines = ["%-24s %14s %14s %14s" % ("Device", "InUse", "Peak", "Limit")]
    for d in jax.local_devices():
        st = memory_stats(d)
        lines.append("%-24s %14d %14d %14d" % (
            str(d), st.get("bytes_in_use", 0),
            st.get("peak_bytes_in_use", 0), st.get("bytes_limit", 0)))
    return "\n".join(lines)
