"""Storage manager (SURVEY.md N2): pooling config, lifecycle, inspection.

Reference analog: ``include/mxnet/storage.h`` + ``src/storage/
pooled_storage_manager.h`` — per-device memory pools with env-tunable
strategy/reserve knobs (``MXNET_GPU_MEM_POOL_TYPE``,
``MXNET_GPU_MEM_POOL_RESERVE``) plus ``DirectFree``/``ReleaseAll``.

TPU-native split of those duties:
  * the *allocator* is PjRt/XLA's BFC pool — its knobs are process-level
    environment settings that must land before backend init;
    :func:`apply_pool_env` translates the reference's env-var surface to
    the XLA client knobs (and is called from ``mxnet_tpu/__init__`` so
    ``MXNET_*`` spellings work for TPU runs too);
  * *lifecycle*: :func:`release_all` is the ReleaseAll/empty-cache
    analog — drops compiled-executable caches and triggers host GC so
    dead device buffers return to the pool;
  * *inspection*: allocator stats, live-buffer census, and a
    ``gpu_memory_info``-style (free, total) pair for the profiler and
    OOM debugging.
"""
from __future__ import annotations

import gc
import os
from typing import Dict, Optional, Tuple

import jax

__all__ = ["apply_pool_env", "memory_stats", "bytes_allocated",
           "bytes_limit", "memory_info", "device_nbytes", "array_buffers",
           "live_arrays", "release_all", "report"]


def apply_pool_env(environ=None) -> Dict[str, str]:
    """Map the reference's memory-pool env knobs onto XLA client settings.

    Must run BEFORE the jax backend initializes (imported from
    ``mxnet_tpu/__init__``).  Mappings:

    - ``MXNET_GPU_MEM_POOL_TYPE=Unpooled`` -> ``XLA_PYTHON_CLIENT_ALLOCATOR=platform``
    - ``MXNET_GPU_MEM_POOL_RESERVE=<pct>`` -> ``XLA_PYTHON_CLIENT_MEM_FRACTION=(100-pct)/100``
    - ``MXNET_TPU_PREALLOCATE=0`` -> ``XLA_PYTHON_CLIENT_PREALLOCATE=false``

    Returns the settings it exported (for tests/logging).  Existing XLA
    settings are never overwritten.
    """
    env = environ if environ is not None else os.environ
    applied = {}
    pool = env.get("MXNET_GPU_MEM_POOL_TYPE", "")
    if pool.lower() == "unpooled" and \
            "XLA_PYTHON_CLIENT_ALLOCATOR" not in env:
        applied["XLA_PYTHON_CLIENT_ALLOCATOR"] = "platform"
    reserve = env.get("MXNET_GPU_MEM_POOL_RESERVE", "")
    if reserve and "XLA_PYTHON_CLIENT_MEM_FRACTION" not in env:
        try:
            frac = max(0.0, min(1.0, (100.0 - float(reserve)) / 100.0))
            applied["XLA_PYTHON_CLIENT_MEM_FRACTION"] = "%.2f" % frac
        except ValueError:
            pass
    if env.get("MXNET_TPU_PREALLOCATE", "") == "0" and \
            "XLA_PYTHON_CLIENT_PREALLOCATE" not in env:
        applied["XLA_PYTHON_CLIENT_PREALLOCATE"] = "false"
    env.update(applied)
    return applied


def _as_device(device):
    """Accept a jax Device or an mxnet Context (Context.jax_device)."""
    if device is None:
        return None
    return getattr(device, "jax_device", device)


def memory_stats(device: Optional[object] = None) -> Dict:
    """Raw allocator stats of a device (PjRt ``memory_stats``); {} when the
    backend doesn't expose them (e.g. CPU).  Accepts a jax Device or an
    mxnet Context."""
    dev = _as_device(device) or jax.devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except (AttributeError, jax.errors.JaxRuntimeError):
        return {}


def bytes_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def bytes_limit(device=None) -> int:
    return int(memory_stats(device).get("bytes_limit", 0))


def memory_info(device=None) -> Tuple[int, int]:
    """(free_bytes, total_bytes) — the ``mx.context.gpu_memory_info``
    analog for the current accelerator."""
    st = memory_stats(device)
    total = int(st.get("bytes_limit", 0))
    used = int(st.get("bytes_in_use", 0))
    return max(total - used, 0), total


def device_nbytes(a, device) -> int:
    """Bytes the array actually holds ON ``device``: the sum of its
    addressable shards there.  A mesh-sharded array contributes only its
    local shard bytes, not the global ``nbytes``, to each device."""
    devs = a.devices()
    if device not in devs:
        return 0
    if len(devs) == 1:
        return a.nbytes
    total = 0
    for sh in a.addressable_shards:
        if sh.device == device and sh.data is not None:
            total += sh.data.nbytes
    return total


def array_buffers(a):
    """``[(device, buffer_ptr_or_None, nbytes)]`` for the array's
    addressable buffers.  The pointer identifies the underlying device
    buffer so callers can dedupe aliases — jax caches per-shard
    ``ArrayImpl`` views on first ``addressable_shards`` access, and
    those views show up in ``jax.live_arrays()`` sharing the parent's
    storage."""
    devs = a.devices()
    if len(devs) == 1:
        try:
            ptr = a.unsafe_buffer_pointer()
        except Exception:
            ptr = None
        return [(next(iter(devs)), ptr, a.nbytes)]
    out = []
    for sh in a.addressable_shards:
        if sh.data is None:
            continue
        try:
            ptr = sh.data.unsafe_buffer_pointer()
        except Exception:
            ptr = None
        out.append((sh.device, ptr, sh.data.nbytes))
    return out


def live_arrays(device=None) -> Tuple[int, int]:
    """(count, total_bytes) of live jax arrays, optionally filtered to one
    device — the storage manager's live-allocation census.  Per-device
    totals count addressable shard bytes (see :func:`device_nbytes`) and
    each underlying device buffer exactly once (aliasing shard views are
    skipped), so summing over devices matches the global figure instead
    of multiply-counting sharded arrays."""
    device = _as_device(device)
    arrays = []
    for a in jax.live_arrays():
        try:
            arrays.append(array_buffers(a))
        except Exception:       # deleted/donated buffers
            continue
    # parents before their cached shard views: the view's single buffer
    # is then already seen and skipped
    arrays.sort(key=len, reverse=True)
    seen = set()
    count = 0
    total = 0
    for bufs in arrays:
        contributed = 0
        for d, ptr, nbytes in bufs:
            if ptr is not None:
                key = (id(d), ptr)
                if key in seen:
                    continue
                seen.add(key)
            if device is not None and d != device:
                continue
            contributed += nbytes
        if contributed:
            count += 1
            total += contributed
    return count, total


def release_all() -> None:
    """ReleaseAll/empty-cache analog: drop compiled-executable caches and
    collect host garbage so dead device buffers return to the pool.
    (Live NDArrays are untouched — PjRt frees buffers on refcount zero.)
    """
    gc.collect()
    jax.clear_caches()
    gc.collect()


def report() -> str:
    """Human-readable per-device memory table (the
    ``MXAggregateProfileStatsPrint`` memory-section analog)."""
    lines = ["%-24s %14s %14s %14s" % ("Device", "InUse", "Peak", "Limit")]
    for d in jax.local_devices():
        st = memory_stats(d)
        lines.append("%-24s %14d %14d %14d" % (
            str(d), st.get("bytes_in_use", 0),
            st.get("peak_bytes_in_use", 0), st.get("bytes_limit", 0)))
    return "\n".join(lines)
