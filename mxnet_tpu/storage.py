"""Storage manager (SURVEY.md N2): pooling config, lifecycle, inspection.

Reference analog: ``include/mxnet/storage.h`` + ``src/storage/
pooled_storage_manager.h`` — per-device memory pools with env-tunable
strategy/reserve knobs (``MXNET_GPU_MEM_POOL_TYPE``,
``MXNET_GPU_MEM_POOL_RESERVE``) plus ``DirectFree``/``ReleaseAll``.

TPU-native split of those duties:
  * the *allocator* is PjRt/XLA's BFC pool — its knobs are process-level
    environment settings that must land before backend init;
    :func:`apply_pool_env` translates the reference's env-var surface to
    the XLA client knobs (and is called from ``mxnet_tpu/__init__`` so
    ``MXNET_*`` spellings work for TPU runs too);
  * *lifecycle*: :func:`release_all` is the ReleaseAll/empty-cache
    analog — drops compiled-executable caches and triggers host GC so
    dead device buffers return to the pool;
  * *inspection*: allocator stats, live-buffer census, and a
    ``gpu_memory_info``-style (free, total) pair for the profiler and
    OOM debugging.
"""
from __future__ import annotations

import gc
import os
from typing import Dict, Optional, Tuple

import jax

__all__ = ["apply_pool_env", "memory_stats", "bytes_allocated",
           "bytes_limit", "memory_info", "live_arrays", "release_all",
           "report"]


def apply_pool_env(environ=None) -> Dict[str, str]:
    """Map the reference's memory-pool env knobs onto XLA client settings.

    Must run BEFORE the jax backend initializes (imported from
    ``mxnet_tpu/__init__``).  Mappings:

    - ``MXNET_GPU_MEM_POOL_TYPE=Unpooled`` -> ``XLA_PYTHON_CLIENT_ALLOCATOR=platform``
    - ``MXNET_GPU_MEM_POOL_RESERVE=<pct>`` -> ``XLA_PYTHON_CLIENT_MEM_FRACTION=(100-pct)/100``
    - ``MXNET_TPU_PREALLOCATE=0`` -> ``XLA_PYTHON_CLIENT_PREALLOCATE=false``

    Returns the settings it exported (for tests/logging).  Existing XLA
    settings are never overwritten.
    """
    env = environ if environ is not None else os.environ
    applied = {}
    pool = env.get("MXNET_GPU_MEM_POOL_TYPE", "")
    if pool.lower() == "unpooled" and \
            "XLA_PYTHON_CLIENT_ALLOCATOR" not in env:
        applied["XLA_PYTHON_CLIENT_ALLOCATOR"] = "platform"
    reserve = env.get("MXNET_GPU_MEM_POOL_RESERVE", "")
    if reserve and "XLA_PYTHON_CLIENT_MEM_FRACTION" not in env:
        try:
            frac = max(0.0, min(1.0, (100.0 - float(reserve)) / 100.0))
            applied["XLA_PYTHON_CLIENT_MEM_FRACTION"] = "%.2f" % frac
        except ValueError:
            pass
    if env.get("MXNET_TPU_PREALLOCATE", "") == "0" and \
            "XLA_PYTHON_CLIENT_PREALLOCATE" not in env:
        applied["XLA_PYTHON_CLIENT_PREALLOCATE"] = "false"
    env.update(applied)
    return applied


def _as_device(device):
    """Accept a jax Device or an mxnet Context (Context.jax_device)."""
    if device is None:
        return None
    return getattr(device, "jax_device", device)


def memory_stats(device: Optional[object] = None) -> Dict:
    """Raw allocator stats of a device (PjRt ``memory_stats``); {} when the
    backend doesn't expose them (e.g. CPU).  Accepts a jax Device or an
    mxnet Context."""
    dev = _as_device(device) or jax.devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except (AttributeError, jax.errors.JaxRuntimeError):
        return {}


def bytes_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def bytes_limit(device=None) -> int:
    return int(memory_stats(device).get("bytes_limit", 0))


def memory_info(device=None) -> Tuple[int, int]:
    """(free_bytes, total_bytes) — the ``mx.context.gpu_memory_info``
    analog for the current accelerator."""
    st = memory_stats(device)
    total = int(st.get("bytes_limit", 0))
    used = int(st.get("bytes_in_use", 0))
    return max(total - used, 0), total


def live_arrays(device=None) -> Tuple[int, int]:
    """(count, total_bytes) of live jax arrays, optionally filtered to one
    device — the storage manager's live-allocation census."""
    device = _as_device(device)
    count = 0
    total = 0
    for a in jax.live_arrays():
        try:
            if device is not None and device not in a.devices():
                continue
            count += 1
            total += a.nbytes
        except Exception:       # deleted/donated buffers
            continue
    return count, total


def release_all() -> None:
    """ReleaseAll/empty-cache analog: drop compiled-executable caches and
    collect host garbage so dead device buffers return to the pool.
    (Live NDArrays are untouched — PjRt frees buffers on refcount zero.)
    """
    gc.collect()
    jax.clear_caches()
    gc.collect()


def report() -> str:
    """Human-readable per-device memory table (the
    ``MXAggregateProfileStatsPrint`` memory-section analog)."""
    lines = ["%-24s %14s %14s %14s" % ("Device", "InUse", "Peak", "Limit")]
    for d in jax.local_devices():
        st = memory_stats(d)
        lines.append("%-24s %14d %14d %14d" % (
            str(d), st.get("bytes_in_use", 0),
            st.get("peak_bytes_in_use", 0), st.get("bytes_limit", 0)))
    return "\n".join(lines)
