"""Sharded / async checkpointing over orbax-tensorstore.

Reference analog + upgrade (SURVEY.md §5.4): the reference checkpoints are
``prefix-symbol.json`` + ``prefix-%04d.params`` NDArray maps
(model.py save_checkpoint / load_checkpoint — kept, implemented in
``mxnet_tpu/model.py`` over the npz save format).  This module is the
"better" tier the TPU build targets: orbax-backed checkpoints that
 - store SHARDED jax.Arrays without gathering to one host (multi-pod safe),
 - restore with the original shardings (or new ones for resharding),
 - optionally write asynchronously, overlapping with training.

    ckpt = mx.checkpoint.save_sharded("/ckpt/step100", net)   # or a dict
    mx.checkpoint.load_sharded("/ckpt/step100", net)
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Union

import jax
import numpy as np

from .base import MXNetError

__all__ = ["save_sharded", "load_sharded", "AsyncCheckpointer"]


def _as_pytree(obj) -> Dict[str, jax.Array]:
    """Accept a Gluon Block, a ParameterDict, or a {name: NDArray/array}
    dict; return {name: jax.Array}."""
    from .ndarray.ndarray import NDArray
    if hasattr(obj, "collect_params"):
        obj = obj.collect_params()
    if hasattr(obj, "items"):
        out = {}
        for k, v in obj.items():
            # Parameter (callable .data) — NOT numpy's .data memoryview
            if hasattr(v, "data") and callable(getattr(v, "data")):
                v = v.data()
            out[k] = v._data if isinstance(v, NDArray) else jax.numpy.asarray(v)
        return out
    raise MXNetError("expected a Block, ParameterDict or dict, got %r"
                     % type(obj))


def save_sharded(path: str, params, *, force: bool = True):
    """Write a sharded orbax checkpoint of ``params`` at ``path``.

    Each process writes only its own shards (no host gather) — the
    multi-pod-safe path the reference's single-file .params format can't
    express.
    """
    import orbax.checkpoint as ocp
    tree = _as_pytree(params)
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=force)
    return path


def load_sharded(path: str, target=None):
    """Restore a sharded checkpoint.

    target: a Block/ParameterDict/dict to restore INTO (values get the
    checkpointed data, placed with their current shardings), or None to
    return the raw {name: jax.Array} dict.
    """
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        tree = _as_pytree(target)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None)),
            tree)
        restored = ckptr.restore(path, abstract)
    # write back into the target's parameters
    from .ndarray.ndarray import NDArray
    obj = target.collect_params() if hasattr(target, "collect_params") \
        else target
    for k, v in restored.items():
        slot = obj[k]
        if hasattr(slot, "data"):           # Parameter
            slot.data()._data = v
        elif isinstance(slot, NDArray):
            slot._data = v
        else:
            obj[k] = v
    return restored


class AsyncCheckpointer:
    """Asynchronous checkpoint writer (orbax AsyncCheckpointer): ``save``
    returns immediately and the write overlaps training; ``wait`` (or
    close/exit) blocks until durable — the §5.3 'better than reference'
    recovery story."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path: str, params, *, force: bool = True):
        self._ckptr.save(os.path.abspath(path), _as_pytree(params),
                         force=force)
        return path

    def wait(self):
        self._ckptr.wait_until_finished()

    def close(self):
        self.wait()
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
