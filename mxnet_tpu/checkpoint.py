"""Sharded / async checkpointing over orbax-tensorstore.

Reference analog + upgrade (SURVEY.md §5.4): the reference checkpoints are
``prefix-symbol.json`` + ``prefix-%04d.params`` NDArray maps
(model.py save_checkpoint / load_checkpoint — kept, implemented in
``mxnet_tpu/model.py`` over the npz save format).  This module is the
"better" tier the TPU build targets: orbax-backed checkpoints that
 - store SHARDED jax.Arrays without gathering to one host (multi-pod safe),
 - restore with the original shardings (or new ones for resharding),
 - optionally write asynchronously, overlapping with training.

    ckpt = mx.checkpoint.save_sharded("/ckpt/step100", net)   # or a dict
    mx.checkpoint.load_sharded("/ckpt/step100", net)
"""
from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
from typing import Dict, Optional, Union

import jax
import numpy as np

from .base import MXNetError, get_env
from . import telemetry as _telemetry

__all__ = ["save_sharded", "load_sharded", "AsyncCheckpointer",
           "TrainCheckpointer", "install_preempt_handler", "preempted",
           "clear_preempt", "COMMIT_MARKER"]

_CKPT_WRITES = _telemetry.counter(
    "checkpoint_writes_total",
    "Training checkpoints committed", ("mode",))
_CKPT_SKIPS = _telemetry.counter(
    "checkpoint_skips_total",
    "Checkpoint opportunities skipped because a write was in flight")


def _as_pytree(obj) -> Dict[str, jax.Array]:
    """Accept a Gluon Block, a ParameterDict, or a {name: NDArray/array}
    dict; return {name: jax.Array}."""
    from .ndarray.ndarray import NDArray
    if hasattr(obj, "collect_params"):
        obj = obj.collect_params()
    if hasattr(obj, "items"):
        out = {}
        for k, v in obj.items():
            # Parameter (callable .data) — NOT numpy's .data memoryview
            if hasattr(v, "data") and callable(getattr(v, "data")):
                v = v.data()
            out[k] = v._data if isinstance(v, NDArray) else jax.numpy.asarray(v)
        return out
    raise MXNetError("expected a Block, ParameterDict or dict, got %r"
                     % type(obj))


def save_sharded(path: str, params, *, force: bool = True):
    """Write a sharded orbax checkpoint of ``params`` at ``path``.

    Each process writes only its own shards (no host gather) — the
    multi-pod-safe path the reference's single-file .params format can't
    express.
    """
    import orbax.checkpoint as ocp
    tree = _as_pytree(params)
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=force)
    return path


def load_sharded(path: str, target=None):
    """Restore a sharded checkpoint.

    target: a Block/ParameterDict/dict to restore INTO (values get the
    checkpointed data, placed with their current shardings), or None to
    return the raw {name: jax.Array} dict.
    """
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        tree = _as_pytree(target)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None)),
            tree)
        restored = ckptr.restore(path, abstract)
    # write back into the target's parameters
    from .ndarray.ndarray import NDArray
    obj = target.collect_params() if hasattr(target, "collect_params") \
        else target
    for k, v in restored.items():
        slot = obj[k]
        if hasattr(slot, "data") and callable(getattr(slot, "data")):
            # Parameter: validate against the live value, then go through
            # set_data so EVERY context replica gets the restored value (a
            # raw ``.data()._data = v`` used to overwrite one replica and
            # silently accept dtype/shape drift)
            cur = slot.data()
            _check_restored(k, cur, v)
            slot.set_data(NDArray(jax.numpy.asarray(v), cur.context))
        elif isinstance(slot, NDArray):
            _check_restored(k, slot, v)
            slot._data = v
        else:
            obj[k] = v
    return restored


def _check_restored(name, cur, v):
    """A restored leaf must match the live parameter exactly — a silent
    dtype cast or shape broadcast here corrupts training state in a way
    that only shows up as a diverging loss much later."""
    if tuple(cur.shape) != tuple(np.shape(v)):
        raise MXNetError(
            "checkpoint restore: %r has shape %s, parameter expects %s"
            % (name, tuple(np.shape(v)), tuple(cur.shape)))
    if np.dtype(cur.dtype) != np.dtype(getattr(v, "dtype", None)):
        raise MXNetError(
            "checkpoint restore: %r has dtype %s, parameter expects %s"
            % (name, getattr(v, "dtype", None), np.dtype(cur.dtype)))


class AsyncCheckpointer:
    """Asynchronous checkpoint writer (orbax AsyncCheckpointer): ``save``
    returns immediately and the write overlaps training; ``wait`` (or
    close/exit) blocks until durable — the §5.3 'better than reference'
    recovery story."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path: str, params, *, force: bool = True):
        self._ckptr.save(os.path.abspath(path), _as_pytree(params),
                         force=force)
        return path

    def wait(self):
        self._ckptr.wait_until_finished()

    def close(self):
        self.wait()
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---- periodic training checkpoints (donation-safe, commit-marked) ---------

#: a checkpoint step dir without this file is an in-progress or torn write
#: and must be invisible to restore
COMMIT_MARKER = "COMMIT.json"

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")

_preempt = threading.Event()
_preempt_installed = False


def install_preempt_handler(signum=signal.SIGTERM):
    """Make SIGTERM (the preemption notice on every major scheduler) set a
    flag the training loop polls between steps: finish the in-flight step,
    write a final sync checkpoint, exit 0.  Chains any existing handler.
    No-op off the main thread (signal API restriction)."""
    global _preempt_installed
    if _preempt_installed:
        return True
    try:
        prev = signal.getsignal(signum)

        def _handler(sig, frame):
            _preempt.set()
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(sig, frame)

        signal.signal(signum, _handler)
    except ValueError:
        return False
    _preempt_installed = True
    return True


def preempted():
    return _preempt.is_set()


def clear_preempt():
    _preempt.clear()


def latest_checkpoint_dir(directory):
    """Newest COMMITTED ``step_<N>`` dir under ``directory`` (or None).
    Uncommitted/partial dirs — no marker — are skipped, never loaded."""
    if not directory or not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        m = _STEP_DIR_RE.match(name)
        if not m:
            continue
        path = os.path.join(directory, name)
        if not os.path.exists(os.path.join(path, COMMIT_MARKER)):
            continue
        if int(m.group(1)) > best_step:
            best_step, best = int(m.group(1)), path
    return best


class TrainCheckpointer:
    """Periodic, donation-safe, async training checkpoints.

    The caller snapshots its state into host copies (the snapshot happens
    BEFORE the next fused step donates the live buffers — after ``step``
    returns, params/opt-state reference the step's freshly-materialized
    outputs, and converting them to numpy forces the D2H copy while they
    are still valid).  The write then overlaps training on orbax's async
    machinery; a ``COMMIT.json`` marker lands only after the write is
    durable, so ``latest()`` can never hand back a torn checkpoint.

    Layout per checkpoint::

        <dir>/step_<N>/state/         orbax tree (params + aux)
        <dir>/step_<N>/<name>.bin     opaque blobs (e.g. pickled updater
                                      states — written on the async thread)
        <dir>/step_<N>/COMMIT.json    {"step": N, "meta": {...}}, last

    Retention is keep-last-K over COMMITTED checkpoints; stale uncommitted
    dirs (from a crash mid-write) are pruned too.
    """

    def __init__(self, directory, every_n_steps=None, keep=None):
        self._dir = os.path.abspath(directory)
        self._every = int(get_env("MXNET_CKPT_EVERY_N_STEPS", 0)
                          if every_n_steps is None else every_n_steps)
        self._keep = int(get_env("MXNET_CKPT_KEEP", 3)
                         if keep is None else keep)
        os.makedirs(self._dir, exist_ok=True)
        self._async = AsyncCheckpointer()
        self._pending: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls):
        """The ``Module.fit``/``Trainer.fit_epoch`` wiring:
        ``MXNET_CKPT_DIR`` + ``MXNET_CKPT_EVERY_N_STEPS`` > 0 opt in."""
        directory = os.environ.get("MXNET_CKPT_DIR")
        every = int(get_env("MXNET_CKPT_EVERY_N_STEPS", 0))
        if not directory or every <= 0:
            return None
        return cls(directory, every_n_steps=every)

    @property
    def directory(self):
        return self._dir

    def due(self, step):
        return self._every > 0 and step > 0 and step % self._every == 0

    def busy(self):
        t = self._pending
        return t is not None and t.is_alive()

    def maybe_save(self, step, tree, meta=None, blobs=None):
        """Async checkpoint; returns False (and counts a skip) when the
        previous write is still in flight — a slow filesystem must cost a
        checkpoint, never stall the training step."""
        if self.busy():
            _CKPT_SKIPS.inc()
            return False
        self._start_write(step, tree, meta, blobs, sync=False)
        return True

    def save_sync(self, step, tree, meta=None, blobs=None):
        """Blocking checkpoint (the preempt path: the process is about to
        exit, so overlap buys nothing and durability is everything)."""
        self.wait()
        self._start_write(step, tree, meta, blobs, sync=True)
        return os.path.join(self._dir, "step_%d" % int(step))

    def _start_write(self, step, tree, meta, blobs, sync):
        step = int(step)
        path = os.path.join(self._dir, "step_%d" % step)
        if os.path.isdir(path):
            # leftover from a crashed attempt at the same step (it cannot
            # be committed: latest() would have resumed past it)
            shutil.rmtree(path, ignore_errors=True)
        tree = dict(tree)
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            # snapshot leaves that are still device arrays (numpy host
            # copies are skipped by tag) are checkpoint-owned until the
            # async write drops them
            _memwatch.tag("checkpoint", tree)

        def _finish():
            # the orbax submit itself (directory creation, serialization
            # setup) costs real milliseconds — off the training thread
            # too.  Safe: ``tree`` holds host snapshots the caller never
            # mutates, and busy()/wait() serialize access to ``_async``.
            self._async.save(os.path.join(path, "state"), tree)
            self._async.wait()
            for name, payload in (blobs or {}).items():
                with open(os.path.join(path, name), "wb") as f:
                    f.write(payload)
            marker = {"step": step, "meta": dict(meta or {})}
            tmp = os.path.join(path, COMMIT_MARKER + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(marker, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(path, COMMIT_MARKER))
            _CKPT_WRITES.labels(mode="sync" if sync else "async").inc()
            try:
                from . import runlog as _runlog
                _runlog.event("checkpoint_commit", step=step,
                              sync=bool(sync))
            except Exception:
                pass
            self._prune()

        if sync:
            _finish()
        else:
            t = threading.Thread(target=_finish, daemon=True,
                                 name="mxnet-ckpt-commit")
            t.start()
            self._pending = t

    def wait(self):
        t = self._pending
        if t is not None:
            t.join()
            self._pending = None

    def latest(self):
        """Path of the newest committed checkpoint, or None."""
        return latest_checkpoint_dir(self._dir)

    def load(self, path):
        """Read one committed checkpoint: ``(tree, meta, blobs)``."""
        marker = os.path.join(path, COMMIT_MARKER)
        if not os.path.exists(marker):
            raise MXNetError(
                "checkpoint %r has no commit marker (partial write?)"
                % path)
        with open(marker, "r", encoding="utf-8") as f:
            meta = json.load(f).get("meta", {})
        import orbax.checkpoint as ocp
        with ocp.StandardCheckpointer() as ckptr:
            tree = ckptr.restore(os.path.join(path, "state"))
        blobs = {}
        for name in os.listdir(path):
            if name.endswith(".bin"):
                with open(os.path.join(path, name), "rb") as f:
                    blobs[name] = f.read()
        return tree, meta, blobs

    def _prune(self):
        """Keep-last-K committed checkpoints; also reap uncommitted dirs
        older than the newest committed one (torn writes from a crash)."""
        with self._lock:
            committed, partial = [], []
            try:
                names = os.listdir(self._dir)
            except OSError:
                return
            for name in names:
                m = _STEP_DIR_RE.match(name)
                if not m:
                    continue
                step = int(m.group(1))
                path = os.path.join(self._dir, name)
                if os.path.exists(os.path.join(path, COMMIT_MARKER)):
                    committed.append((step, path))
                else:
                    partial.append((step, path))
            committed.sort()
            doomed = [p for _, p in committed[:-self._keep]] \
                if self._keep > 0 else []
            if committed:
                newest = committed[-1][0]
                doomed += [p for s, p in partial if s < newest]
            for p in doomed:
                shutil.rmtree(p, ignore_errors=True)

    def close(self):
        self.wait()
        self._async.close()
