"""Optimizers (parity: python/mxnet/optimizer.py — registry at :35,112, the
SGD..Nadam zoo at :444-1446, and the ``Updater`` with state (de)serialization
at :1464).  Each dense update dispatches to a fused op from
``ops/optimizer_ops.py`` — one XLA fusion per parameter, matching the
reference's fused optimizer kernels (src/operator/optimizer_op.cc)."""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import numpy as np

from .base import Registry, MXNetError
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["Optimizer", "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD",
           "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax",
           "Nadam", "LBSGD", "Test", "Updater", "get_updater", "create",
           "register"]

_registry = Registry("optimizer")


def register(klass):
    _registry.register(klass.__name__, klass)
    return klass


class Optimizer:
    """Base optimizer (ref optimizer.py:Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = 0
        self.param_dict = param_dict or {}
        self.idx2name = dict(param_idx2name or {})
        self.sym_info = ()
        if sym is not None:
            self.sym_info = (sym.attr_dict(), sym.list_arguments())
        # reference Optimizer.__init__ applies symbol-attr multipliers
        self.set_lr_mult({})
        self.set_wd_mult({})

    create_optimizer = None  # set below

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        from . import amp as _amp
        if self.multi_precision and _amp.is_low_precision(weight.dtype):
            w32 = weight.astype(np.float32)
            state = (self.create_state(index, w32), w32)
        else:
            state = self.create_state(index, weight)
        from . import memwatch as _memwatch
        if _memwatch.enabled and state is not None:
            # every update path (eager Updater, fused step, Trainer mesh)
            # funnels state creation through here — the one ledger hook
            _memwatch.tag("opt_state", state)
        return state

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        """Generic multi-precision step (the fused path's parity oracle):
        the fp32 update runs against the master copy with the fp32-cast
        gradient, then the low-precision weight is re-cast from the new
        master.  Optimizers with dedicated mp kernels (SGD) override."""
        if self._mp_state(weight, state):
            inner, w32 = state
            self.update(index, w32, grad.astype(np.float32), inner)
            w32.copyto(weight)
            return
        self.update(index, weight, grad, state)

    def _mp_state(self, weight, state):
        """Whether ``state`` is the eager multi-precision layout
        ``(inner_state, master_fp32)`` for this low-precision weight."""
        from . import amp as _amp
        return (self.multi_precision and _amp.is_low_precision(weight.dtype)
                and isinstance(state, tuple) and len(state) == 2
                and isinstance(state[1], NDArray)
                and state[1].dtype == np.float32)

    def set_learning_rate(self, lr):
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    # ---- (param, device) slot resolution --------------------------------
    # The eager updater keys its state (and therefore lr_mult/wd_mult
    # lookups through ``idx2name``) by a flattened (param, device) slot.
    # Both the eager call sites and the fused step must agree on this
    # layout or per-name multipliers silently stop applying on replicas.

    @staticmethod
    def slot_index(param_idx, num_device=1, device=0):
        """Flattened updater-state slot for param ``param_idx`` on device
        ``device`` when weights are replicated over ``num_device`` devices."""
        return param_idx * num_device + device

    @staticmethod
    def build_idx2name(param_names, num_device=1):
        """``idx2name`` covering every (param, device) slot, so
        ``_get_lr``/``_get_wd`` resolve the same name for all replicas."""
        idx2name = {}
        for i, name in enumerate(param_names):
            for k in range(num_device):
                idx2name[Optimizer.slot_index(i, num_device, k)] = name
        return idx2name

    # ---- functional (traceable) core for the fused train step -----------
    # ``fused_update`` is the jit-traceable twin of ``update``: pure jax
    # arrays in, (new_weight, new_state_leaves) out, no NDArray wrappers,
    # no count/lr bookkeeping (the driver resolves lr/wd/t per slot and
    # passes them in, traced, so one compiled program serves every step).

    def supports_fused(self, weight):
        """Whether ``update`` has a traceable twin for this weight."""
        return False

    def fused_state_arity(self):
        """Number of state leaves ``fused_update`` expects/returns."""
        return None

    def fused_update(self, weight, grad, state, lr, wd, rescale, t):
        """Pure update: ``(w, g, state_leaves, lr, wd, rescale, t)`` ->
        ``(new_w, new_state_leaves)``.  All array args are jax values."""
        raise MXNetError("%s has no fused update" % type(self).__name__)

    def fused_mp(self, weight):
        """Whether this weight rides the fused path in multi-precision
        form: low-precision storage with a master-fp32 leaf PREPENDED to
        its flat state tuple, updated via ``fused_update_mp``."""
        from . import amp as _amp
        return self.multi_precision and _amp.is_low_precision(weight.dtype)

    def fused_update_mp(self, weight, grad, state, lr, wd, rescale, t):
        """Multi-precision twin of ``fused_update``: ``state[0]`` is the
        master-fp32 copy, the rest are the optimizer's own leaves.  The
        update runs in fp32 against the master (grad up-cast first) and
        the low-precision weight is re-cast from the new master — the
        traced mirror of the eager ``update_multi_precision`` oracle."""
        import jax.numpy as jnp
        master = state[0]
        new_master, inner = self.fused_update(
            master, grad.astype(jnp.float32), tuple(state[1:]),
            lr, wd, rescale, t)
        return (new_master.astype(weight.dtype),
                (new_master,) + tuple(inner))

    def fused_slot_lr(self, lr, t):
        """Per-slot learning rate with any host-side correction folded in
        (Adam's f64 bias correction).  The fused drivers capture lr
        through this hook so the traced programs see exactly the lr the
        eager update computes on the host — the master-fp32 trajectory
        stays bit-identical to the eager oracle."""
        return lr

    def atlas_scope_name(self):
        """Name the atlas uses for this optimizer's update stage inside
        fused programs (``Optimizer::<name>``).  Override to disambiguate
        wrappers/subclasses that share a class name."""
        return type(self).__name__

    def _fused_dtype_ok(self, weight):
        # fp32 weights always; low-precision weights only in
        # multi-precision mode, where the update runs in f32 against the
        # master leaf prepended to the state tuple (fused_update_mp).
        # Low-precision WITHOUT a master stays on the eager oracle:
        # traced f32 scalars (lr/wd/t) would promote fp16 arithmetic to
        # f32 where eager weak python floats keep it in fp16.
        return weight.dtype == np.float32 or self.fused_mp(weight)

    def _fused_attrs(self, lr, wd, rescale):
        # clip_gradient must stay a static python float: _prep_grad branches
        # on ``>= 0`` at trace time (-1.0 is the kernels' "disabled" value)
        return {"lr": lr, "wd": wd, "rescale_grad": rescale,
                "clip_gradient": -1.0 if self.clip_gradient is None
                else float(self.clip_gradient)}

    def _update_rows(self, index, weight, grad, state):
        """Lazy update for a row_sparse gradient (reference: the sparse
        FComputeEx optimizer kernels, src/operator/optimizer_op.cc — only
        rows present in ``grad.indices`` are touched): slice the occupied
        rows, run this optimizer's *dense* update on the row block (one XLA
        gather → fused update → scatter), write the rows back."""
        import numpy as _np
        import jax.numpy as jnp
        from .ndarray.sparse import RowSparseNDArray
        idx = grad._sp_indices
        if len(idx) == 0:
            self._update_count(index)
            return
        sparse_weight = isinstance(weight, RowSparseNDArray)
        if sparse_weight:
            # map grad rows to positions inside the weight's value block;
            # every grad row must be present (reference requires the weight's
            # occupancy to cover pushed rows — kvstore pulls them first)
            pos = _np.searchsorted(weight._sp_indices, idx)
            if (pos >= len(weight._sp_indices)).any() or \
                    (weight._sp_indices[_np.minimum(
                        pos, len(weight._sp_indices) - 1)] != idx).any():
                raise MXNetError("row_sparse weight is missing rows present "
                                 "in the gradient; row_sparse_pull them "
                                 "first")
            jidx_w = jnp.asarray(pos)
            w_block = weight._sp_values
        else:
            jidx_w = jnp.asarray(idx)
            w_block = weight._data
        # states are dense full-shape arrays indexed by row id
        jidx = jnp.asarray(idx)

        def rows(a):
            return NDArray(a._data[jidx], a.context) \
                if isinstance(a, NDArray) else a

        w_rows = NDArray(w_block[jidx_w], weight.context)
        g_rows = NDArray(grad._sp_values.astype(weight.dtype), weight.context)
        s_rows = tuple(rows(s) for s in state) if isinstance(state, tuple) \
            else rows(state)
        self.update(index, w_rows, g_rows, s_rows)
        if sparse_weight:
            weight._sp_values = weight._sp_values.at[jidx_w].set(w_rows._data)
        else:
            weight._data = weight._data.at[jidx_w].set(w_rows._data)
        states = state if isinstance(state, tuple) else (state,)
        srows = s_rows if isinstance(s_rows, tuple) else (s_rows,)
        for s, sr in zip(states, srows):
            if isinstance(s, NDArray):
                s._data = s._data.at[jidx].set(sr._data)

    @staticmethod
    def _is_row_sparse(grad):
        from .ndarray.sparse import RowSparseNDArray
        return isinstance(grad, RowSparseNDArray)


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision (ref optimizer.py:444)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        if self._is_row_sparse(grad):
            return self._update_rows(index, weight, grad, state)
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None and isinstance(state, tuple):
            mom, w32 = state
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, w32, out=weight,
                                     momentum=self.momentum, **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, out=weight, **kw)
        elif state is not None:
            nd.sgd_mom_update(weight, grad, state, out=weight,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, **kw)

    update_multi_precision = update

    def supports_fused(self, weight):
        return self._fused_dtype_ok(weight)

    def fused_state_arity(self):
        return 1 if self.momentum != 0.0 else 0

    def fused_update(self, weight, grad, state, lr, wd, rescale, t):
        from .ops import optimizer_ops as _ops
        attrs = self._fused_attrs(lr, wd, rescale)
        if state:
            attrs["momentum"] = self.momentum
            w, m = _ops._sgd_mom_update(attrs, weight, grad, state[0])
            return w, (m,)
        return _ops._sgd_update(attrs, weight, grad), ()


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            nd.signum_update(weight, grad, state, out=weight,
                             momentum=self.momentum, wd_lh=self.wd_lh, **kw)
        else:
            nd.signsgd_update(weight, grad, out=weight, **kw)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            nd.nag_mom_update(weight, grad, state, out=weight,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, **kw)

    def supports_fused(self, weight):
        return self._fused_dtype_ok(weight)

    def fused_state_arity(self):
        return 1 if self.momentum != 0.0 else 0

    def fused_update(self, weight, grad, state, lr, wd, rescale, t):
        from .ops import optimizer_ops as _ops
        attrs = self._fused_attrs(lr, wd, rescale)
        if state:
            attrs["momentum"] = self.momentum
            w, m = _ops._nag_mom_update(attrs, weight, grad, state[0])
            return w, (m,)
        return _ops._sgd_update(attrs, weight, grad), ()


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        if self._is_row_sparse(grad):
            return self._update_rows(index, weight, grad, state)
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        # bias correction folded into lr (reference Adam.update)
        kw["lr"] *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=weight,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, **kw)

    def supports_fused(self, weight):
        return self._fused_dtype_ok(weight)

    def fused_state_arity(self):
        return 2

    def fused_slot_lr(self, lr, t):
        # bias correction folded into lr exactly as the eager update does
        # it — host-side f64, so the traced program and the eager oracle
        # consume bit-identical lr values.  t is a per-slot host count at
        # capture time; the correction never enters the trace.
        return lr * math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)

    def fused_update(self, weight, grad, state, lr, wd, rescale, t):
        from .ops import optimizer_ops as _ops
        attrs = self._fused_attrs(lr, wd, rescale)
        attrs.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)
        mean, var = state
        w, m, v = _ops._adam_update(attrs, weight, grad, mean, var)
        return w, (m, v)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = nd.zeros(weight.shape, weight.context, dtype=weight.dtype)
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype), z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        d, v, z = state
        nd.ftml_update(weight, grad, d, v, z, out=weight, beta1=self.beta1,
                       beta2=self.beta2, epsilon=self.epsilon, t=t, **kw)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, weight.context, dtype=weight.dtype)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, out=weight,
                                  gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon, **kw)
        else:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=weight, gamma1=self.gamma1,
                              epsilon=self.epsilon, **kw)

    def supports_fused(self, weight):
        return self._fused_dtype_ok(weight)

    def fused_state_arity(self):
        return 3 if self.centered else 1

    def fused_update(self, weight, grad, state, lr, wd, rescale, t):
        from .ops import optimizer_ops as _ops
        attrs = self._fused_attrs(lr, wd, rescale)
        attrs.update(gamma1=self.gamma1, epsilon=self.epsilon,
                     clip_weights=-1.0 if not self.clip_weights
                     else float(self.clip_weights))
        if self.centered:
            attrs["gamma2"] = self.gamma2
            n, g, delta = state
            w, nn, ng, ndelta = _ops._rmspropalex_update(
                attrs, weight, grad, n, g, delta)
            return w, (nn, ng, ndelta)
        (n,) = state
        w, nn = _ops._rmsprop_update(attrs, weight, grad, n)
        return w, (nn,)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        if self._is_row_sparse(grad):
            return self._update_rows(index, weight, grad, state)
        self._update_count(index)
        kw = self._common_kwargs(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=weight, lamda1=self.lamda1,
                       beta=self.beta, **kw)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        if self._is_row_sparse(grad):
            return self._update_rows(index, weight, grad, state)
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        state += g * g
        weight -= lr * g / (state.sqrt() + self.float_stable_eps)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1 - self.rho) * g * g
        delta = (acc_delta + self.epsilon).sqrt() / \
            (acc_g + self.epsilon).sqrt() * g
        acc_delta[:] = self.rho * acc_delta + (1 - self.rho) * delta * delta
        weight[:] = weight - delta - wd * weight


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        m, u = state
        m[:] = self.beta1 * m + (1 - self.beta1) * g
        u[:] = nd.maximum(self.beta2 * u, g.abs())
        weight -= lr * m / u


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        mt = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mtn = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= mt
        sched_next = self.m_schedule * mtn
        m, v = state
        m[:] = self.beta1 * m + (1 - self.beta1) * g
        v[:] = self.beta2 * v + (1 - self.beta2) * g * g
        g_prime = g / (1 - self.m_schedule)
        m_prime = m / (1 - sched_next)
        v_prime = v / (1 - self.beta2 ** t)
        weight -= lr * (mtn * m_prime + (1 - mt) * g_prime) / \
            (v_prime.sqrt() + self.epsilon)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        weight[:] = weight - lr / 2 * (g + wd * weight) + \
            nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                             dtype=weight.dtype)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        mom, prev = state
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is not None:
            mom[:] = self.momentum * mom - lr * comp
            update = mom
        else:
            update = -lr * comp
        prev[:] = weight
        weight += update


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style warmup (ref optimizer.py LBSGD);
    dense path delegates to SGD with the layer-wise-scaled lr."""

    def __init__(self, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(**kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


def fused_state_leaves(state, mp=False):
    """Flatten an updater state into a tuple of NDArray leaves for the
    fused step (``None`` -> ``()``); returns ``None`` when the structure
    isn't fusable, signalling fallback to the eager oracle.

    With ``mp=True`` the state must be the eager multi-precision layout
    ``(inner_state, master_fp32)``; the flat fused layout PREPENDS the
    master — ``(master, *inner_leaves)`` — matching what
    ``fused_update_mp`` consumes and returns.  (The master can't ride
    LAST: ``fused_update_mp`` slices ``state[1:]`` for the wrapped
    optimizer, and a positional convention keeps the slot shape
    independent of the inner arity.)
    """
    if mp:
        if not (isinstance(state, (tuple, list)) and len(state) == 2
                and isinstance(state[1], NDArray)):
            return None
        inner = fused_state_leaves(state[0])
        if inner is None:
            return None
        return (state[1],) + inner
    if state is None:
        return ()
    if isinstance(state, NDArray):
        return (state,)
    if isinstance(state, (tuple, list)):
        leaves = []
        for s in state:
            if not isinstance(s, NDArray):
                return None
            leaves.append(s)
        return tuple(leaves)
    return None


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _registry.get(name)(**kwargs)


Optimizer.create_optimizer = staticmethod(create)


class Updater:
    """Callable (index, grad, weight) applying the optimizer with per-index
    state, (de)serializable (ref optimizer.py:1464)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            # eager updates repoint weight/state handles at fresh program
            # outputs each step — re-ledger them or the tags die with the
            # old buffers
            _memwatch.tag("params", weight)
            if self.states[index] is not None:
                _memwatch.tag("opt_state", self.states[index])

    def set_states(self, states):
        states = pickle.loads(states) if isinstance(states, bytes) else states
        if isinstance(states, tuple) and len(states) == 2:
            states, self.optimizer.num_update = states

        def to_nd(s):
            if isinstance(s, np.ndarray):
                return nd.array(s)
            if isinstance(s, (tuple, list)):
                return type(s)(to_nd(x) for x in s)
            return s

        self.states = {k: to_nd(v) for k, v in states.items()}
        self.states_synced = {k: True for k in self.states}

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return type(s)(to_np(x) for x in s)
            return s
        states = {k: to_np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer.num_update)
                            if dump_optimizer else states)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
