"""Chaos-injection harness: env-gated fault injection for resilience tests.

A framework that survives worker death, server death, and preemption has
to *prove* it — by inspection nothing hangs; under injected faults the
hang is found in CI instead of production.  This module is the single
switchboard for every injectable fault, all OFF unless ``MXNET_CHAOS=1``:

wire level (hooks inside ``kvstore_server.send_msg`` — both directions,
worker->server requests and server->worker replies):

  * ``MXNET_CHAOS_FRAME_DROP_P``    — drop the frame (never sent); the
    peer's deadline-aware recv times out and the retry path replays it.
  * ``MXNET_CHAOS_FRAME_DELAY_P`` / ``MXNET_CHAOS_FRAME_DELAY_MS`` —
    sleep before the send (straggling link).
  * ``MXNET_CHAOS_FRAME_CORRUPT_P`` — flip a byte in the frame header
    region so the receiver's framing validation rejects it loudly
    (``kvstore_frame_errors_total``) and the client reconnects.

process level (hooks the training loop / server push path call):

  * ``MXNET_CHAOS_DIE_AT_STEP``     — ``os._exit(1)`` when the worker
    reaches that step (the kill -9 analog: no cleanup, no atexit).
  * ``MXNET_CHAOS_SIGTERM_AT_STEP`` — SIGTERM self-delivery at that step
    (preemption analog; the checkpoint preempt handler must catch it).
  * ``MXNET_CHAOS_DIE_AT_PUSH``     — server-side: ``os._exit(1)`` after
    that many applied pushes (parameter-server death mid-run).

``MXNET_CHAOS_ONLY_GEN`` scopes every injection to one elastic restart
generation (``MXNET_ELASTIC_RESTART``), so a relaunched gang runs clean —
the canonical "fail once, recover, converge" experiment.  Faults draw
from a process-local PRNG seeded by ``MXNET_CHAOS_SEED`` + pid (set the
seed for reproducible fault schedules).  Every injection increments
``chaos_injections_total{kind}`` and, when a run ledger is open, appends
a ``chaos_injection`` runlog event for the post-mortem timeline.
"""
from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Optional

from . import telemetry as _telemetry

__all__ = ["active", "wire_action", "corrupt", "delay_seconds", "step",
           "server_push"]

_INJECTIONS = _telemetry.counter(
    "chaos_injections_total",
    "Faults injected by the chaos harness", ("kind",))

_rng_lock = threading.Lock()
_rng: Optional[random.Random] = None


def _get_rng() -> random.Random:
    global _rng
    with _rng_lock:
        if _rng is None:
            seed = os.environ.get("MXNET_CHAOS_SEED")
            _rng = random.Random(
                (int(seed) + os.getpid()) if seed else None)
        return _rng


def _p(name: str) -> float:
    try:
        return max(0.0, min(1.0, float(os.environ.get(name, "0") or 0)))
    except ValueError:
        return 0.0


def active() -> bool:
    """Master gate: faults only ever fire under ``MXNET_CHAOS=1``, and
    only in the elastic generation ``MXNET_CHAOS_ONLY_GEN`` names (any
    generation when unset)."""
    if os.environ.get("MXNET_CHAOS", "0") in ("0", "", "false", "off"):
        return False
    only_gen = os.environ.get("MXNET_CHAOS_ONLY_GEN")
    if only_gen not in (None, ""):
        return os.environ.get("MXNET_ELASTIC_RESTART", "0") == only_gen
    return True


def _note(kind: str):
    _INJECTIONS.labels(kind=kind).inc()
    try:
        from . import runlog as _runlog
        _runlog.event("chaos_injection", kind=kind)
    except Exception:
        pass


def wire_action() -> Optional[str]:
    """One draw of the wire-fault die for a frame about to be sent:
    ``"drop"`` / ``"delay"`` / ``"corrupt"`` / None.  The caller owns the
    mechanics (skip the send / sleep / flip bytes); this function owns
    probability, accounting, and the ledger event."""
    if not active():
        return None
    r = _get_rng().random()
    p_drop = _p("MXNET_CHAOS_FRAME_DROP_P")
    p_corrupt = _p("MXNET_CHAOS_FRAME_CORRUPT_P")
    p_delay = _p("MXNET_CHAOS_FRAME_DELAY_P")
    if r < p_drop:
        _note("frame_drop")
        return "drop"
    if r < p_drop + p_corrupt:
        _note("frame_corrupt")
        return "corrupt"
    if r < p_drop + p_corrupt + p_delay:
        _note("frame_delay")
        return "delay"
    return None


def corrupt(payload: bytes) -> bytes:
    """Flip one byte in the frame-header region (first 64 bytes past the
    length prefix) so the receiver's framing validation catches it loudly
    instead of silently accepting corrupted tensor bytes."""
    if not payload:
        return payload
    idx = _get_rng().randrange(min(64, len(payload)))
    b = bytearray(payload)
    b[idx] ^= 0xFF
    return bytes(b)


def delay_seconds() -> float:
    try:
        return max(0.0, float(
            os.environ.get("MXNET_CHAOS_FRAME_DELAY_MS", "50"))) / 1e3
    except ValueError:
        return 0.05


def _at(name: str, value: int) -> bool:
    raw = os.environ.get(name)
    if not raw:
        return False
    try:
        return int(raw) == int(value)
    except ValueError:
        return False


def step(step_no: int):
    """Training-loop hook: die / self-preempt when the configured step is
    reached.  Call once per completed step with the global step number."""
    if not active():
        return
    if _at("MXNET_CHAOS_DIE_AT_STEP", step_no):
        _note("die_at_step")
        os._exit(1)
    if _at("MXNET_CHAOS_SIGTERM_AT_STEP", step_no):
        _note("sigterm_at_step")
        os.kill(os.getpid(), signal.SIGTERM)
        # delivery is asynchronous: give the handler a beat so the "at
        # step N" contract holds before step N+1 dispatches
        time.sleep(0.05)


def server_push(push_count: int):
    """Parameter-server hook: die (kill -9 analog) after the configured
    number of applied pushes."""
    if not active():
        return
    if _at("MXNET_CHAOS_DIE_AT_PUSH", push_count):
        _note("die_at_push")
        os._exit(1)
