"""SequentialModule: chain of modules (parity:
python/mxnet/module/sequential_module.py).  Rarely used; provided for API
completeness with forward/backward chaining."""
from __future__ import annotations

import logging

from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger)
        self._modules = []
        self._metas = []

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        return self

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        shapes = data_shapes
        for i, mod in enumerate(self._modules):
            take_labels = self._metas[i].get(self.META_TAKE_LABELS, False)
            mod.bind(shapes, label_shapes if take_labels else None,
                     for_training, inputs_need_grad or i > 0,
                     force_rebind, grad_req=grad_req)
            # next module consumes this module's outputs: rewire the data
            # descriptors to the output shapes (auto_wiring semantics of
            # the reference sequential_module.py)
            from ..io import DataDesc
            data_names = (mod.data_names if i + 1 >= len(self._modules)
                          else self._modules[i + 1].data_names)
            shapes = [DataDesc(dn, s)
                      for dn, (_n, s) in zip(data_names, mod.output_shapes)]
        self.binded = True
        self.for_training = for_training

    def init_params(self, **kwargs):
        for mod in self._modules:
            mod.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        for mod in self._modules:
            mod.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        from ..io import DataBatch
        batch = data_batch
        for mod in self._modules:
            mod.forward(batch, is_train)
            outs = mod.get_outputs()
            batch = DataBatch(outs, data_batch.label)

    def backward(self, out_grads=None):
        for mod in reversed(self._modules):
            mod.backward(out_grads)
            out_grads = mod.get_input_grads()

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_params(self):
        arg, aux = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def update_metric(self, eval_metric, labels):
        self._modules[-1].update_metric(eval_metric, labels)
