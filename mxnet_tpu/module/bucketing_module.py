"""BucketingModule: per-bucket executors sharing parameters.

Reference analog: ``python/mxnet/module/bucketing_module.py:36`` — variable-
length sequence training where each bucket (sequence length) gets its own
bound executor but all share parameters.  On TPU each bucket is its own XLA
compilation (static shapes); the jit cache makes switching cheap after the
first visit — exactly the per-bucket-compile pattern SURVEY.md §7.3 calls out.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    def _gen_module(self, bucket_key):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(sym, data_names, label_names, self.logger,
                         self._context, self._work_load_list,
                         self._fixed_param_names)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind, grad_req=grad_req)
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        assert self.binded
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training,
                     self.inputs_need_grad)
            if self.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.init_params(arg_params=arg, aux_params=aux,
                                allow_missing=False, force_init=True)
            if self._curr_module.optimizer_initialized:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod._kvstore = self._curr_module._kvstore
                mod._update_on_kvstore = self._curr_module._update_on_kvstore
                mod.optimizer_initialized = True
        elif self.params_initialized and self._params_dirty:
            arg, aux = self._curr_module.get_params()
            mod.init_params(arg_params=arg, aux_params=aux, force_init=True)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, **kwargs):
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is not None and key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)
        self._params_dirty = True

    def update(self):
        self._curr_module.update()
        self._params_dirty = True

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_params(self):
        return self._curr_module.get_params()

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
