"""Module: the symbolic training harness over executor groups + KVStore.

Reference analog: ``python/mxnet/module/module.py`` (bind:364,
init_optimizer:473, update:643 — SURVEY.md §3.1): binds a Symbol on a list
of contexts, slices batches, reduces gradients through KVStore, applies the
optimizer either locally or on the kvstore (``update_on_kvstore``).
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from .. import optimizer as opt
from .. import kvstore as kvs
from .. import fused_step as _fused
from .. import telemetry as _telemetry
from .. import health as _health
from ..context import Context, cpu, current_context
from ..initializer import InitDesc
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None, mesh_axes=None,
                 sharding_rules=None):
        super().__init__(logger)
        if context is None:
            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params = None
        self._aux_params = None
        self._exec_group: Optional[DataParallelExecutorGroup] = None
        self._optimizer = None
        self._kvstore = None
        self._updater = None
        self._update_on_kvstore = False
        self._grad_req = "write"
        self._group2ctxs = group2ctxs
        self._fused_step = None
        # mesh layout for the GSPMD multi-device fused step: axis sizes
        # (e.g. {"dp": 4, "tp": 2}; default pure-DP over all contexts) and
        # optional parallel.mesh.ShardingRules for the params
        self._mesh_axes = dict(mesh_axes) if mesh_axes else None
        self._sharding_rules = sharding_rules

    def set_mesh(self, mesh_axes, sharding_rules=None):
        """Select the device-mesh layout (axis-name → size) and optional
        parameter ShardingRules for the multi-device fused step.  Takes
        effect on the next update(); the step program is re-specialised
        (new jit-cache key) for the new layout."""
        self._mesh_axes = dict(mesh_axes) if mesh_axes else None
        self._sharding_rules = sharding_rules
        if self._fused_step is not None:
            self._fused_step.on_mesh_change()

    # ---- info -----------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        shapes = {d.name: d.shape for d in self._exec_group.data_shapes}
        for l in (self._exec_group.label_shapes or []):
            shapes[l.name] = l.shape
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self._symbol.list_outputs(), out_shapes))

    # ---- bind / init ----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        from ..io import DataDesc
        data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                       for d in data_shapes]
        if label_shapes:
            label_shapes = [l if isinstance(l, DataDesc) else DataDesc(*l)
                            for l in label_shapes]
        self._grad_req = grad_req
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        from .. import amp
        type_dict = None
        if amp.enabled():
            # bind-time dtype policy: params/data bf16, labels and
            # normalization scale/shift fp32 (see amp.type_dict_for)
            type_dict = amp.type_dict_for(
                self._symbol, self._data_names,
                [l.name for l in (label_shapes or [])])
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            group2ctxs=self._group2ctxs, type_dict=type_dict)
        self.binded = True
        if self._arg_params is not None:
            self._exec_group.set_params(self._arg_params, self._aux_params,
                                        allow_extra=True)

    def init_params(self, initializer="__default__", arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        if initializer == "__default__":
            # reference default (base_module.py:629): Uniform(0.01) — a bare
            # init_params() must NOT leave weights at zero (relu nets would
            # never break symmetry); name-based dispatch in Initializer
            # still zeroes biases and sets moving stats correctly
            from .. import initializer as init_mod
            initializer = init_mod.Uniform(0.01)
        ex = self._exec_group.execs[0]
        self._arg_params = {n: ex.arg_dict[n].copyto(cpu())
                            for n in self._param_names}
        self._aux_params = {n: ex.aux_dict[n].copyto(cpu())
                            for n in self._aux_names}
        attrs = self._symbol.attr_dict()

        def _fill(params, source):
            for name, arr in params.items():
                if source is not None and name in source:
                    source[name].copyto(arr)
                elif source is not None and not allow_missing:
                    # reference semantics: a provided param source must cover
                    # every parameter unless allow_missing
                    raise MXNetError("parameter %r missing from provided "
                                     "params (allow_missing=False)" % name)
                elif initializer is not None:
                    initializer(InitDesc(name, attrs.get(name, {})), arr)

        _fill(self._arg_params, arg_params)
        _fill(self._aux_params, aux_params)
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)
        from .. import memwatch as _memwatch
        if _memwatch.enabled:
            # ledger: the device-resident parameter buffers (every exec's
            # arg/aux dicts) plus the host master copies above — both are
            # live jax buffers and both belong to the params budget
            for e in self._exec_group.execs:
                _memwatch.tag("params", (e.arg_dict, e.aux_dict))
            _memwatch.tag("params", (self._arg_params, self._aux_params),
                          detail="host_master")
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            batch_size = self._exec_group.batch_size
            if not isinstance(kvstore, str) and kvstore is not None and \
                    "dist" in kvstore.type and "_sync" in kvstore.type:
                batch_size *= kvstore.num_workers
            params = dict(optimizer_params)
            # reference default (module.py init_optimizer): grads are
            # batch-summed, so rescale by 1/batch unless caller overrides
            params.setdefault("rescale_grad", 1.0 / batch_size)
            # one updater-state slot per (param, device); the shared
            # resolver keeps this layout in lockstep with the update paths
            idx2name = opt.Optimizer.build_idx2name(
                self._param_names, len(self._context))
            optimizer = opt.create(optimizer, sym=self._symbol,
                                   param_idx2name=idx2name, **params)
        self._optimizer = optimizer
        kv = kvstore
        if isinstance(kvstore, str):
            kv = kvs.create(kvstore) if kvstore else None
        self._kvstore = kv
        # update_on_kvstore decision (ref model.py:_create_kvstore):
        # dist stores apply updates kvstore-side
        self._update_on_kvstore = bool(kv) and kv.type.startswith("dist")
        self._updater = None if self._update_on_kvstore \
            else opt.get_updater(optimizer)
        if kv:
            if self._update_on_kvstore:
                kv.set_optimizer(optimizer)
            for i, name in enumerate(self._param_names):
                kv.init(name, self._arg_params[name])
                # sync back: on dist stores rank 0's init wins, so every
                # rank must start from the store's value (reference
                # model.py _initialize_kvstore pulls after init)
                if kv.type.startswith("dist"):
                    weights = self._exec_group.param_arrays[i]
                    kv.pull(name, out=weights)
                    kv.pull(name, out=self._arg_params[name])
        self.optimizer_initialized = True
        preload = getattr(self, "_preload_opt_states", None)
        if preload is not None and self._updater is not None:
            with open(preload, "rb") as f:
                self._updater.set_states(f.read())
            self._preload_opt_states = None
        self._fused_step = _fused.ModuleFusedStep(self) \
            if self._updater is not None else None

    # ---- step -----------------------------------------------------------
    def _fused(self):
        """Fused-step driver, recreated after a force_rebind (the driver's
        donation pools and cached programs belong to one executor group)."""
        fs = self._fused_step
        if fs is not None and fs.stale():
            fs = self._fused_step = _fused.ModuleFusedStep(self)
        return fs

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        fs = self._fused()
        if fs is not None:
            fs.flush_eager()
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        fs = self._fused()
        if fs is not None:
            fs.flush_eager()
        self._exec_group.backward(out_grads)

    def forward_backward(self, data_batch):
        fs = self._fused()
        if fs is not None and fs.eligible():
            # defer: update() fuses this batch's fwd+bwd with the
            # optimizer update into one donated XLA program.  Only an
            # un-consumed previous batch forces an eager replay — an
            # unconditional flush would also de-mesh between every pair
            # of mesh steps, breaking the donation chain
            if fs.pending:
                fs.flush_eager()
            fs.stage(data_batch)
            return
        if fs is not None:
            fs.flush_eager()
        self._exec_group.forward_backward(data_batch)

    def update(self):
        """KVStore reduce + optimizer (ref module.py:643-670 + SURVEY 3.1).

        With MXNET_TPU_FUSED_STEP (default ON) and a local updater this
        dispatches the fused whole-step program staged by
        forward_backward; the per-param loop below is the OFF fallback and
        parity oracle.  Note the fused path does not materialize gradients
        in grad_dict (they live only inside the program)."""
        assert self.optimizer_initialized
        tel = _telemetry.enabled
        t0 = time.perf_counter() if tel else 0.0
        fs = self._fused()
        if fs is not None and fs.pending and fs.eligible():
            path = fs.step()
            if path:
                if tel:
                    _fused.STEP_DISPATCH.labels(path=path).inc()
                    _fused.STEP_TIME.observe(time.perf_counter() - t0)
                if _health.enabled:
                    _health.monitor.on_step(
                        "mesh_step" if path == "mesh_fused" else
                        ("step" if len(self._context) == 1
                         else ("fwdbwd", "update")))
                return
        if fs is not None:
            fs.flush_eager()
        eg = self._exec_group
        ndev = len(self._context)
        if self._kvstore is not None:
            # batched push/pull: one call over all param names lets the
            # dist_async wire layer coalesce messages into buckets
            live = [i for i, g in enumerate(eg.grad_arrays) if g]
            names = [self._param_names[i] for i in live]
            grads_l = [eg.grad_arrays[i] for i in live]
            weights_l = [eg.param_arrays[i] for i in live]
            if names:
                self._kvstore.push(names, grads_l)
                if self._update_on_kvstore:
                    self._kvstore.pull(names, out=weights_l)
                else:
                    # pull the reduced gradient back into each device grad
                    self._kvstore.pull(names, out=grads_l)
            if not self._update_on_kvstore:
                for i, grads, weights in zip(live, grads_l, weights_l):
                    for k, (w, g) in enumerate(zip(weights, grads)):
                        # per-device optimizer state, slot resolvable
                        # through idx2name (shared resolver)
                        self._updater(
                            opt.Optimizer.slot_index(i, ndev, k), g, w)
        else:
            for i, (name, grads, weights) in enumerate(
                    zip(self._param_names, eg.grad_arrays, eg.param_arrays)):
                for k, (w, g) in enumerate(zip(weights, grads)):
                    self._updater(
                        opt.Optimizer.slot_index(i, ndev, k), g, w)
        from .. import memwatch as _memwatch
        if _memwatch.enabled:
            # kvstore pull / eager ops repoint grad buffers at fresh
            # program outputs — re-ledger them or the tags die with the
            # old buffers
            for grads in eg.grad_arrays:
                for g in grads or ():
                    _memwatch.tag("activations", g)
        if tel:
            _fused.STEP_DISPATCH.labels(path="eager").inc()
            _fused.STEP_TIME.observe(time.perf_counter() - t0)
        if _health.enabled:
            _health.monitor.on_step(("fwdbwd",))

    def get_outputs(self, merge_multi_context=True):
        fs = self._fused()
        if fs is not None:
            outs = fs.mesh_outputs()
            if outs is not None:
                # the mesh step produced full-batch outputs directly — no
                # per-device concat needed (and the per-exec outputs are
                # stale, the program never ran per device)
                return outs if merge_multi_context else [[o] for o in outs]
            fs.flush_eager()
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        fs = self._fused()
        if fs is not None:
            fs.flush_eager()
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        fs = self._fused()
        if fs is not None:
            outs = fs.mesh_outputs()
            if outs is not None:
                eval_metric.update(list(labels), outs)
                return
            fs.flush_eager()
        self._exec_group.update_metric(eval_metric, labels)

    def defer_metric_update(self, eval_metric, labels):
        """Capture this step's outputs/labels and return a zero-arg
        closure performing the metric update LATER — the overlapped fit
        loop (train_loop.OverlappedLoop) runs it a few steps behind
        dispatch so the metric's hard D2H never stalls the next step.
        Returns None when deferring would not be equivalent (multi-device
        eager group, whose outputs are rebound per step)."""
        fs = self._fused()
        if fs is not None:
            outs = fs.mesh_outputs()
            if outs is not None:
                labels = list(labels)
                return lambda: eval_metric.update(labels, outs)
            fs.flush_eager()
        eg = self._exec_group
        if len(eg.execs) != 1:
            return None
        lab = [l[eg.slices[0]] for l in labels]
        outs = list(eg.execs[0].outputs)
        return lambda: eval_metric.update(lab, outs)

    def get_params(self):
        assert self.binded and self.params_initialized
        fs = self._fused()
        if fs is not None:
            # mesh globals back to per-device replicas so the averaging
            # below never mixes 8-device and single-device commitments
            fs.demesh()
        arg, aux = {}, {}
        self._exec_group.get_params(arg, aux)
        return arg, aux

    def install_monitor(self, mon):
        for ex in self._exec_group.execs:
            mon.install(ex)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            with open("%s-%04d.states" % (prefix, epoch), "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        # loaded params count as initialized (reference module.py:160) —
        # a later fit()/init_params() must NOT re-randomize them
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod
