"""BaseModule: the training-harness interface + fit loop.

Reference analog: ``python/mxnet/module/base_module.py`` (fit at :399-560,
score/predict/forward_backward), the epoch loop whose hot path is SURVEY.md
§3.1.  The loop structure (data iter → forward_backward → update →
update_metric → callbacks → epoch-end sync/checkpoint) is preserved.
"""
from __future__ import annotations

import logging
import time
from typing import Any, List, Optional

import numpy as np

from ..base import MXNetError
from .. import metric as metric_mod
from .. import ndarray as nd
from ..callback import BatchEndParam

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.inputs_need_grad = False
        self._symbol = None

    # ---- abstract -------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def bind(self, *a, **kw):
        raise NotImplementedError

    def init_params(self, *a, **kw):
        raise NotImplementedError

    def init_optimizer(self, *a, **kw):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # ---- composite ------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                _call(batch_end_callback,
                      BatchEndParam(epoch, nbatch, eval_metric))
        if score_end_callback is not None:
            _call(score_end_callback, BatchEndParam(epoch, nbatch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outs = [o[0:o.shape[0] - pad].copy() for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concatenate([b[i] for b in output_list], axis=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, overlap_depth=None):
        """The reference fit loop (base_module.py:399-560).

        ``overlap_depth`` > 0 (default from ``MXNET_IO_OVERLAP_DEPTH``)
        defers each step's blocking tail — metric D2H + batch callback —
        behind that many dispatched steps, so the device never idles on
        host-side bookkeeping.  Side effects still run in exact step
        order; pass 0 for the fully serial reference loop.  A monitor
        forces the serial loop (it must observe each step synchronously).
        """
        assert num_epoch is not None, "num_epoch must be specified"
        from .. import initializer as init_mod
        from ..train_loop import OverlappedLoop, default_overlap_depth
        initializer = initializer or init_mod.Uniform(0.01)
        depth = default_overlap_depth() if overlap_depth is None \
            else max(0, int(overlap_depth))
        overlap = (depth > 0 and monitor is None
                   and hasattr(self, "defer_metric_update"))
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # fault tolerance: periodic donation-safe async checkpoints
        # (MXNET_CKPT_DIR + MXNET_CKPT_EVERY_N_STEPS), preempt-resume
        # (SIGTERM -> final sync checkpoint -> exit 0), and the chaos
        # harness's per-step process faults
        from .. import chaos as _chaos
        from .. import checkpoint as _ckpt
        ckpt = _ckpt.TrainCheckpointer.from_env()
        gstep = 0
        skip_batches = 0
        if ckpt is not None:
            _ckpt.install_preempt_handler()
            latest = ckpt.latest()
            if latest is not None:
                tree, meta, blobs = ckpt.load(latest)
                self._ft_restore(tree, meta, blobs)
                gstep = int(meta.get("global_step", 0))
                begin_epoch = max(begin_epoch, int(meta.get("epoch", 0)))
                skip_batches = int(meta.get("nbatch", 0))
                self.logger.info(
                    "Resumed from %s (epoch %d, batch %d, step %d)",
                    latest, begin_epoch, skip_batches, gstep)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            loop = OverlappedLoop(depth) if overlap else None
            for data_batch in train_data:
                if nbatch < skip_batches:
                    # data-iter cursor fast-forward: the checkpointed
                    # epoch already consumed these batches
                    nbatch += 1
                    continue
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                gstep += 1
                _chaos.step(gstep)
                if ckpt is not None:
                    if _ckpt.preempted():
                        # preemption notice: the step above is complete,
                        # so snapshot it durably and hand back exit 0 (a
                        # clean handoff, not a failure)
                        ckpt.save_sync(
                            gstep,
                            *self._ft_snapshot(epoch, nbatch + 1, gstep))
                        ckpt.close()
                        raise SystemExit(0)
                    if ckpt.due(gstep):
                        ckpt.maybe_save(
                            gstep,
                            *self._ft_snapshot(epoch, nbatch + 1, gstep))
                deferred = None
                if loop is not None:
                    deferred = self.defer_metric_update(
                        eval_metric, data_batch.label)
                if deferred is not None:
                    # blocking tail (metric D2H + callback) runs `depth`
                    # steps behind dispatch, in exact step order
                    def _tail(_d=deferred, _i=nbatch, _e=epoch):
                        _d()
                        if batch_end_callback is not None:
                            _call(batch_end_callback,
                                  BatchEndParam(_e, _i, eval_metric))
                    loop.push(_tail)
                else:
                    self.update_metric(eval_metric, data_batch.label)
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        _call(batch_end_callback,
                              BatchEndParam(epoch, nbatch, eval_metric))
                nbatch += 1
            if loop is not None:
                loop.drain()
            skip_batches = 0  # fast-forward applies to the resume epoch only
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
        if ckpt is not None:
            ckpt.close()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # ---- fault-tolerant training state ----------------------------------
    def _ft_snapshot(self, epoch, nbatch, gstep):
        """Capture params + opt state + cursor as HOST copies, safe to
        hand to an async writer: ``get_params`` is de-mesh-aware (mesh
        globals are repointed to per-device arrays first) and the
        ``asnumpy``/``get_states`` conversions below force the D2H copy
        while the step's output buffers are still valid — before the next
        fused step donates them.  Returns ``(tree, meta, blobs)`` for
        :class:`~mxnet_tpu.checkpoint.TrainCheckpointer`."""
        arg, aux = self.get_params()
        tree = {}
        for k, v in arg.items():
            tree["param/%s" % k] = v.asnumpy()
        for k, v in aux.items():
            tree["aux/%s" % k] = v.asnumpy()
        meta = {"epoch": int(epoch), "nbatch": int(nbatch),
                "global_step": int(gstep)}
        blobs = {}
        updater = getattr(self, "_updater", None)
        if updater is not None:
            blobs["opt_states.bin"] = updater.get_states(
                dump_optimizer=False)
            optimizer = getattr(self, "_optimizer", None)
            if optimizer is not None:
                # Updater.get_states drops the per-slot update counts; an
                # Adam resume without them restarts bias correction at
                # t=0 and is NOT bit-exact — carry them in the marker
                meta["index_update_count"] = {
                    str(k): int(v)
                    for k, v in optimizer._index_update_count.items()}
                meta["num_update"] = int(optimizer.num_update)
        return tree, meta, blobs

    def _ft_restore(self, tree, meta, blobs):
        """Inverse of :meth:`_ft_snapshot` on a bound module: write params
        into every executor, rebuild updater states, restore the
        optimizer's update counts (bit-exact lr schedules / Adam t)."""
        arg = {k[len("param/"):]: nd.array(v) for k, v in tree.items()
               if k.startswith("param/")}
        aux = {k[len("aux/"):]: nd.array(v) for k, v in tree.items()
               if k.startswith("aux/")}
        self.set_params(arg, aux, force_init=True)
        updater = getattr(self, "_updater", None)
        if updater is not None and "opt_states.bin" in (blobs or {}):
            updater.set_states(blobs["opt_states.bin"])
            # pickled state dict keys arrive as-is, but slot indices may
            # have been JSON-stringified in the meta — normalize to int
            optimizer = getattr(self, "_optimizer", None)
            if optimizer is not None:
                counts = meta.get("index_update_count") or {}
                optimizer._index_update_count = {
                    (int(k) if str(k).lstrip("-").isdigit() else k): int(v)
                    for k, v in counts.items()}
                if "num_update" in meta:
                    optimizer.num_update = int(meta["num_update"])
                updater.optimizer = optimizer

    def install_monitor(self, mon):
        raise NotImplementedError

    # ---- io info --------------------------------------------------------
    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _call(callbacks, param):
    for cb in _as_list(callbacks):
        cb(param)
