"""DataParallelExecutorGroup: per-device executors + batch slicing.

Reference analog: ``python/mxnet/module/executor_group.py`` (_split_input_
slice/_load_data, SURVEY.md §3.1).  On TPU, single-device groups dominate
(multi-chip goes through ``parallel.DataParallelTrainer``'s one-pjit-step
path instead), but the multi-context slicing semantics are kept so
``Module(context=[...])`` and KVStore-based updates behave like the
reference on N devices.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..context import Context
from ..io import DataDesc

__all__ = ["DataParallelExecutorGroup", "_split_input_slice"]


def _split_input_slice(batch_size: int, work_load_list: Sequence[float]):
    """Split [0, batch_size) into per-device slices (ref executor_group.py)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        n = int(round(batch_size * w / total)) if i < len(work_load_list) - 1 \
            else batch_size - start
        slices.append(slice(start, start + n))
        start += n
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts: List[Context], workload,
                 data_shapes, label_shapes, param_names,
                 for_training, inputs_need_grad, shared_group=None,
                 fixed_param_names=None, grad_req="write", state_names=None,
                 group2ctxs=None, type_dict=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1.0] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [d.name for d in data_shapes]
        self.label_names = [l.name for l in (label_shapes or [])]
        self.batch_size = data_shapes[0].shape[0]
        self.slices = _split_input_slice(self.batch_size, self.workload)
        self.execs = []
        req = {}
        for n in self.arg_names:
            if n in self.data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self.label_names or n in self.fixed_param_names \
                    or not for_training:
                req[n] = "null"
            else:
                req[n] = grad_req if isinstance(grad_req, str) \
                    else grad_req.get(n, "write")
        self.grad_req = req
        # group2ctxs: coarse model-parallel placement per data-parallel
        # replica (ref module.py:31 + AssignContext) — a dict applies to
        # every replica, a list gives one dict per context
        if isinstance(group2ctxs, dict) or group2ctxs is None:
            group2ctxs = [group2ctxs] * len(contexts)
        assert len(group2ctxs) == len(contexts), \
            "group2ctxs must match the number of contexts"
        for ctx, slc, g2c in zip(contexts, self.slices, group2ctxs):
            n_i = slc.stop - slc.start
            shapes = {d.name: (n_i,) + d.shape[1:] for d in data_shapes}
            for l in (label_shapes or []):
                shapes[l.name] = (n_i,) + l.shape[1:]
            self.execs.append(symbol.simple_bind(ctx=ctx, grad_req=req,
                                                 group2ctx=g2c,
                                                 type_dict=type_dict,
                                                 **shapes))
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes

    # ---- param plumbing -------------------------------------------------
    def set_params(self, arg_params, aux_params, allow_extra=False):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params,
                                allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Average params over devices into the given dicts (ref behavior)."""
        for name in self.param_names:
            arrs = [ex.arg_dict[name] for ex in self.execs]
            acc = arrs[0].copy()
            for a in arrs[1:]:
                acc += a.as_in_context(acc.context)
            arg_params[name] = acc / len(arrs)
        for name in self.aux_names:
            arrs = [ex.aux_dict[name] for ex in self.execs]
            acc = arrs[0].copy()
            for a in arrs[1:]:
                acc += a.as_in_context(acc.context)
            aux_params[name] = acc / len(arrs)

    # ---- execution ------------------------------------------------------
    def _load_batch(self, data_batch):
        data = data_batch.data
        label = data_batch.label or []
        # single-device fast path: no slicing — a batch the producer
        # already placed on the right device (PrefetchingIter double
        # buffering) passes through untouched (as_in_context is a no-op
        # when the context matches), so the step pays no re-put
        whole = len(self.slices) == 1
        feeds = []
        for i, slc in enumerate(self.slices):
            feed = {}
            for name, arr in zip(self.data_names, data):
                feed[name] = (arr if whole else
                              arr[slc]).as_in_context(self.contexts[i])
            for name, arr in zip(self.label_names, label):
                feed[name] = (arr if whole else
                              arr[slc]).as_in_context(self.contexts[i])
            feeds.append(feed)
        return feeds

    def forward(self, data_batch, is_train=None):
        is_train = self.for_training if is_train is None else is_train
        for ex, feed in zip(self.execs, self._load_batch(data_batch)):
            ex.forward(is_train=is_train, **feed)

    def forward_backward(self, data_batch):
        """Fused path: one XLA program per device per step."""
        for ex, feed in zip(self.execs, self._load_batch(data_batch)):
            ex.forward_backward(**feed)

    def backward(self, out_grads=None):
        for ex in self.execs:
            ex.backward(out_grads)

    def get_outputs(self, merge_multi_context=True):
        if len(self.execs) == 1:
            return self.execs[0].outputs
        if not merge_multi_context:
            return [[ex.outputs[i] for ex in self.execs]
                    for i in range(len(self.execs[0].outputs))]
        out = []
        for i in range(len(self.execs[0].outputs)):
            parts = [ex.outputs[i].as_in_context(self.contexts[0])
                     for ex in self.execs]
            out.append(nd.concatenate(parts, axis=0))
        return out

    def get_input_grads(self, merge_multi_context=True):
        grads = []
        for name in self.data_names:
            parts = [ex.grad_dict.get(name) for ex in self.execs]
            if merge_multi_context and len(parts) > 1:
                grads.append(nd.concatenate(
                    [p.as_in_context(self.contexts[0]) for p in parts], axis=0))
            else:
                grads.append(parts[0] if len(parts) == 1 else parts)
        return grads

    def update_metric(self, eval_metric, labels):
        for i, (ex, slc) in enumerate(zip(self.execs, self.slices)):
            lab = [l[slc] for l in labels]
            eval_metric.update(lab, ex.outputs)

    @property
    def grad_arrays(self):
        """Per-param list of per-device grad arrays (kvstore push format)."""
        return [[ex.grad_dict[n] for ex in self.execs
                 if n in ex.grad_dict] for n in self.param_names]

    @property
    def param_arrays(self):
        return [[ex.arg_dict[n] for ex in self.execs]
                for n in self.param_names]

    @property
    def aux_arrays(self):
        return [[ex.aux_dict[n] for ex in self.execs]
                for n in self.aux_names]
