"""Modules whose computation is defined in Python rather than a Symbol.

Reference analog: ``python/mxnet/module/python_module.py`` (PythonModule
at :28, PythonLossModule at :243) — the escape hatch used to splice
host-side computations (custom losses, constraint projections) into a
``SequentialModule`` chain while keeping the Module API contract.
"""
from __future__ import annotations

import logging

import numpy as np

from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """A convenient base for modules implemented in Python: parameter-free
    by default, with shape bookkeeping handled here so subclasses only
    override ``_compute_output_shapes`` (+ forward/backward)."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # ---- names/shapes ---------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ---- params (none by default) ---------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """Parameter-free: nothing to initialize, just flip the flag."""
        self.params_initialized = True

    def update(self):
        """Parameter-free by default (reference python_module.py:134)."""

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        """Subclasses computing a loss typically skip metric updates
        (reference: do nothing by default)."""

    # ---- binding --------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        def plain(s):
            # entries may be DataDesc namedtuples (io.provide_data) — keep
            # only the bare shape (reference extracts .shape too)
            return tuple(s.shape) if hasattr(s, "shape") else tuple(s)

        self._data_shapes = [plain(s) for s in data_shapes]
        self._label_shapes = ([plain(s) for s in label_shapes]
                              if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Parameter-free modules have nothing to optimize."""


class PythonLossModule(PythonModule):
    """A loss layer as a module (reference python_module.py:243): forward
    stores the input scores, backward produces the gradient via a
    user-supplied function (or the default identity 'propagate what
    backward() was given')."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        assert len(data_names) == 1
        assert len(label_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        """The loss passes scores through (reference: output shape ==
        data shape)."""
        return [(self._name + "_output", self._data_shapes[0])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "PythonLossModule is a loss head: it originates gradients"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        """Default gradient: d(scores)/dx of cross-entropy-with-softmax if
        a grad_func was not supplied (reference leaves this to the user;
        the softmax form is its documented example)."""
        from .. import ndarray as nd
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(np.asarray(grad))
            self._scores_grad = grad
            return
        scores = self._scores.asnumpy()
        labels = self._labels.asnumpy().astype(np.int64).ravel()
        prob = np.exp(scores - scores.max(axis=1, keepdims=True))
        prob /= prob.sum(axis=1, keepdims=True)
        prob[np.arange(len(labels)), labels] -= 1.0
        self._scores_grad = nd.array(prob / len(labels))

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
