"""Python side of the C API waist (SURVEY.md N17).

Reference analog: ``src/c_api/c_api.cc`` + ``c_api_ndarray.cc`` — the
C ABI's NDArray CRUD, imperative invoke, and op listing (Parts 0-2 of
``include/mxnet/c_api.h``).  ``src/c_api.cc`` embeds CPython (the same
pattern as the predict ABI, ``src/predict.cc``) and calls these functions;
each takes/returns only simple Python types + NDArray objects so the C
marshalling stays mechanical.

Reference dtype codes (``include/mxnet/tensor_blob.h`` / mshadow type
flags): 0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64;
12=bfloat16 is carried as the TPU-native extension.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from . import context as _context
from . import ndarray as nd
from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ops import registry as _registry

_CODE2DT = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
            4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}
_DT2CODE = {v: k for k, v in _CODE2DT.items()}


def _ctx(dev_type: int, dev_id: int) -> _context.Context:
    name = _context.Context.devtype2str.get(int(dev_type))
    if name is None:
        raise MXNetError("unknown device type id %d" % dev_type)
    return _context.Context(name, int(dev_id))


def _np_dtype(code: int) -> np.dtype:
    try:
        name = _CODE2DT[int(code)]
    except KeyError:
        raise MXNetError("unknown dtype code %d" % code)
    if name == "bfloat16":
        import jax.numpy as jnp
        return jnp.bfloat16
    return np.dtype(name)


def create(shape: Sequence[int], dev_type: int, dev_id: int,
           dtype_code: int = 0, delay_alloc: int = 0) -> NDArray:
    """MXNDArrayCreate/CreateEx: an initialized (zero) array on a device.
    XLA has no uninitialized-alloc notion, so delay_alloc is accepted and
    ignored (allocation is lazy inside jax anyway)."""
    return nd.zeros(tuple(int(s) for s in shape), ctx=_ctx(dev_type, dev_id),
                    dtype=_np_dtype(dtype_code))


def copy_from_ptr(addr: int, size: int, handle: NDArray):
    """MXNDArraySyncCopyFromCPU: overwrite the handle's contents *in place*
    from a flat host buffer of ``size`` elements (reference contract:
    CHECK size == array size; the handle object keeps its identity so
    autograd marking and aliases survive)."""
    import ctypes
    if int(size) != handle.size:
        raise MXNetError("SyncCopyFromCPU: %d elements given, array has %d"
                         % (size, handle.size))
    nbytes = handle.size * np.dtype(handle.dtype).itemsize
    buf = (ctypes.c_ubyte * nbytes).from_address(int(addr))
    arr = np.frombuffer(buf, dtype=handle.dtype).reshape(handle.shape)
    # nd.array's astype copy materializes before the ctypes view dies
    handle._data = nd.array(arr, ctx=handle.context,
                            dtype=handle.dtype)._data


def copy_to_ptr(addr: int, size: int, handle: NDArray):
    """MXNDArraySyncCopyToCPU: write the array into a caller buffer of
    ``size`` elements (reference contract: CHECK size == array size — a
    short buffer must error, never overrun)."""
    import ctypes
    if int(size) != handle.size:
        raise MXNetError("SyncCopyToCPU: buffer holds %d elements, array "
                         "has %d" % (size, handle.size))
    src = np.ascontiguousarray(handle.asnumpy())
    ctypes.memmove(int(addr), src.ctypes.data, src.nbytes)


def shape_of(handle: NDArray) -> Tuple[int, ...]:
    return tuple(int(s) for s in handle.shape)


def dtype_code_of(handle: NDArray) -> int:
    name = np.dtype(handle.dtype).name   # 'bfloat16' via ml_dtypes
    code = _DT2CODE.get(name)
    if code is None:
        raise MXNetError("dtype %r has no reference code" % (name,))
    return code


def ctx_of(handle: NDArray) -> Tuple[int, int]:
    c = handle.context
    return int(c.device_typeid), int(c.device_id)


def wait_to_read(handle: NDArray):
    handle.wait_to_read()


def slice_(handle: NDArray, begin: int, end: int) -> NDArray:
    return handle[int(begin):int(end)]


def reshape(handle: NDArray, dims: Sequence[int]) -> NDArray:
    return handle.reshape(tuple(int(d) for d in dims))


def invoke(op_name: str, inputs: Sequence[NDArray],
           param_keys: Sequence[str], param_vals: Sequence[str],
           outs: Sequence[NDArray] = ()) -> List[NDArray]:
    """MXImperativeInvoke: run one registered operator on NDArray inputs
    with string-typed attrs (the reference passes every attr as a string;
    param.coerce parses them exactly like dmlc::Parameter).  Pre-supplied
    ``outs`` receive the results in place (the reference's non-NULL
    *outputs contract — how ``sgd_update(w, g, out=w)`` works over the
    ABI)."""
    from .ndarray.ndarray import invoke as _invoke
    kwargs: Dict[str, str] = dict(zip(param_keys, param_vals))
    out_arg = list(outs) if outs else None
    out = _invoke(op_name, list(inputs), kwargs, out=out_arg)
    if isinstance(out, NDArray):
        return [out]
    return list(out)


def list_ops() -> List[str]:
    """MXListAllOpNames."""
    return _registry.list_ops()


def save(fname: str, handles: Sequence[NDArray],
         keys: Sequence[str]):
    """MXNDArraySave (named dict when keys given, list format otherwise)."""
    if keys:
        nd.save(fname, dict(zip(keys, handles)))
    else:
        nd.save(fname, list(handles))


def load(fname: str) -> Tuple[List[NDArray], List[str]]:
    """MXNDArrayLoad -> (arrays, names); names empty for list format."""
    data = nd.load(fname)
    if isinstance(data, dict):
        # insertion order == save order (nd.load preserves it); the
        # reference MXNDArrayLoad keeps positional order for named saves,
        # so C consumers may rely on it (advisor r04)
        names = list(data)
        return [data[k] for k in names], names
    return list(data), []


def wait_all():
    """MXNDArrayWaitAll/MXEngineWaitAll."""
    import jax
    from . import engine as _engine
    _engine.get().wait_for_all()
    jax.effects_barrier()


def random_seed(seed: int):
    """MXRandomSeed."""
    from . import random as _random
    _random.seed(int(seed))


# ---- autograd (c_api.h Part 2: MXAutograd*) -------------------------------

def autograd_set_recording(flag: int) -> int:
    from . import autograd as _ag
    return int(_ag.set_recording(bool(flag)))


def autograd_set_training(flag: int) -> int:
    from . import autograd as _ag
    return int(_ag.set_training(bool(flag)))


def autograd_mark_variables(handles: Sequence[NDArray]):
    """MXAutogradMarkVariables (grad_req='write'; gradient storage is
    allocated by attach_grad, read back via get_grad)."""
    for h in handles:
        h.attach_grad()


def autograd_backward(heads: Sequence[NDArray], retain_graph: int):
    from . import autograd as _ag
    _ag.backward(list(heads), retain_graph=bool(retain_graph))


def get_grad(handle: NDArray) -> NDArray:
    """MXNDArrayGetGrad: the gradient buffer attached to a variable."""
    g = handle.grad
    if g is None:
        raise MXNetError("array has no gradient (call mark_variables first)")
    return g


# ---- symbol (c_api.h Part 3: MXSymbol*, reference c_api.h:1028) -----------

class _AtomicSymbol:
    """An op + attrs awaiting composition — the reference's
    MXSymbolCreateAtomicSymbol result before MXSymbolCompose fills the
    inputs (nnvm Symbol::CreateFunctor analog)."""

    __slots__ = ("op_name", "attrs")

    def __init__(self, op_name: str, attrs: Dict[str, str]):
        if op_name not in _registry.OPS:
            raise MXNetError("unknown operator %r" % op_name)
        self.op_name = op_name
        self.attrs = attrs


def symbol_create_atomic(op_name: str, keys: Sequence[str],
                         vals: Sequence[str]):
    """MXSymbolCreateAtomicSymbol: op + string attrs, inputs come later
    via compose."""
    return _AtomicSymbol(op_name, dict(zip(keys, vals)))


def symbol_create_variable(name: str):
    """MXSymbolCreateVariable."""
    from . import symbol as sym_mod
    return sym_mod.var(name)


def symbol_compose(handle, name: str, keys: Sequence[str], args):
    """MXSymbolCompose: fill an atomic symbol's inputs (positional when
    ``keys`` is empty, by arg name otherwise).  Returns the composed
    Symbol — the C side swaps it into the same handle (the reference
    mutates the nnvm symbol in place)."""
    from . import symbol as sym_mod
    if isinstance(handle, _AtomicSymbol):
        op = _registry.OPS[handle.op_name]
        fn = getattr(sym_mod, handle.op_name)
        kwargs = dict(handle.attrs)
        if name:
            kwargs["name"] = name
        if keys:
            known = set(op.arg_names or [])
            for k in keys:
                # reference contract: keyword args must name declared
                # inputs ("Keyword argument name not found")
                if known and k not in known:
                    raise MXNetError(
                        "compose %s: keyword argument %r is not an input "
                        "(have %s)" % (handle.op_name, k, sorted(known)))
            kwargs.update(zip(keys, args))
            return fn(**kwargs)
        return fn(*args, **kwargs)
    # composing a full symbol substitutes its free variables
    if keys:
        handle(**dict(zip(keys, args)))
    else:
        handle(*args)
    return handle


def symbol_copy(handle):
    """MXSymbolCopy (deep copy via the JSON round-trip — node names are
    preserved, so bindings stay compatible)."""
    from . import symbol as sym_mod
    return sym_mod.load_json(handle.tojson())


def symbol_list_arguments(handle) -> List[str]:
    if isinstance(handle, _AtomicSymbol):
        return []
    return list(handle.list_arguments())


def symbol_list_outputs(handle) -> List[str]:
    if isinstance(handle, _AtomicSymbol):
        return []
    return list(handle.list_outputs())


def symbol_list_aux(handle) -> List[str]:
    if isinstance(handle, _AtomicSymbol):
        return []
    return list(handle.list_auxiliary_states())


def symbol_get_name(handle) -> str:
    if isinstance(handle, _AtomicSymbol):
        return ""
    return handle.name or ""


def symbol_tojson(handle) -> str:
    return handle.tojson()


def symbol_from_json(js: str):
    from . import symbol as sym_mod
    return sym_mod.load_json(js)


def symbol_infer_shape(handle, keys: Sequence[str], shapes,
                       partial: int = 0):
    """MXSymbolInferShape(Partial) -> (arg_shapes, out_shapes, aux_shapes)
    as lists of int tuples, ordered like list_arguments/outputs/aux."""
    kwargs = {k: tuple(int(d) for d in s) for k, s in zip(keys, shapes)}
    if partial:
        a, o, x = handle.infer_shape_partial(**kwargs)
    else:
        a, o, x = handle.infer_shape(**kwargs)
    conv = lambda ss: [tuple(int(d) for d in (s or ())) for s in ss]
    return conv(a), conv(o), conv(x)


def op_info(op_name: str):
    """MXSymbolGetAtomicSymbolInfo: (description, input arg names,
    param names, param type strings, required flags) — feeds both the C
    introspection call and the cpp-package wrapper generator."""
    op = _registry.OPS[op_name]
    arg_names = list(op.arg_names or [])
    if not arg_names and op.nin not in (None, -1):
        arg_names = ["data%d" % i for i in range(op.nin)] \
            if op.nin > 1 else ["data"]
    pnames, ptypes, preq = [], [], []
    for k, spec in op.params.items():
        if k.startswith("__"):
            continue
        pnames.append(k)
        t = spec.ptype
        if isinstance(t, (list, tuple)):        # enum of string choices
            ptypes.append("{%s}" % ",".join("'%s'" % c for c in t))
        else:
            ptypes.append(t if isinstance(t, str) else t.__name__)
        preq.append(1 if spec.required else 0)
    # key_var_num_args marks ops taking a homogeneous variadic input list:
    # either declared via a literal num_args param (Concat style) or
    # nin==-1 with no named args (add_n/khatri_rao style) — NOT merely
    # optional trailing inputs like FullyConnected's bias (which has
    # arg_names and therefore a fixed wrapper signature)
    variadic = "num_args" in op.params or (op.nin == -1 and not arg_names)
    return (op.doc or "", arg_names, pnames, ptypes, preq,
            1 if variadic else 0)


# ---- executor (c_api.h Part 4: MXExecutor*, reference c_api.h:1483) -------

_GRAD_REQ = {0: "null", 1: "write", 2: "write", 3: "add"}  # OpReqType


def executor_bind(handle, dev_type: int, dev_id: int,
                  arg_handles, grad_handles, grad_req_codes,
                  aux_handles):
    """MXExecutorBind: positional arrays ordered like list_arguments /
    list_auxiliary_states; grad storage handles may contain None (grad_req
    null).  Gradients are written INTO the supplied grad arrays in place,
    so the caller's handles observe them (reference GraphExecutor
    contract)."""
    arg_names = handle.list_arguments()
    aux_names = handle.list_auxiliary_states()
    if len(arg_handles) != len(arg_names):
        raise MXNetError("bind: %d args given, symbol has %d (%s)"
                         % (len(arg_handles), len(arg_names), arg_names))
    if len(aux_handles) != len(aux_names):
        raise MXNetError("bind: %d aux given, symbol has %d"
                         % (len(aux_handles), len(aux_names)))
    args = dict(zip(arg_names, arg_handles))
    req = {n: _GRAD_REQ.get(int(c), "null")
           for n, c in zip(arg_names, grad_req_codes)}
    grads = {n: g for n, g in zip(arg_names, grad_handles)
             if g is not None and req.get(n) != "null"}
    auxs = dict(zip(aux_names, aux_handles))
    return handle.bind(_ctx(dev_type, dev_id), args=args, args_grad=grads,
                       grad_req=req, aux_states=auxs)


def executor_forward(ex, is_train: int):
    ex.forward(is_train=bool(is_train))


def executor_outputs(ex) -> List[NDArray]:
    return list(ex.outputs)


def executor_backward(ex, head_grads):
    ex.backward(out_grads=list(head_grads) if head_grads else None)
