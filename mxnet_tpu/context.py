"""Device context, TPU-first.

Re-design of the reference's ``Context`` (``python/mxnet/context.py``,
``include/mxnet/base.h`` device enum).  The device enum gains ``tpu`` as the
primary accelerator type; ``gpu`` is accepted for source compatibility and is
aliased to the platform accelerator so reference scripts that say
``mx.gpu(0)`` run unchanged on a TPU host.

Mapping to hardware: a ``Context`` resolves to a concrete ``jax.Device``.
``cpu(i)`` maps to host platform device *i* (with
``--xla_force_host_platform_device_count=N`` the host exposes N virtual
devices, which is how multi-device unit tests run without a pod).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus"]


class Context:
    """Device context holding device type and id.

    Parity target: ``mxnet.context.Context`` — usable as a scope
    (``with mx.tpu(0):``), comparable, hashable.
    """

    # devtype enum kept numerically compatible with the reference
    # (include/mxnet/base.h: kCPU=1, kGPU=2, kCPUPinned=3) + kTPU=4.
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu", 5: "cpu_shared"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx

    # ---- JAX device resolution -------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device.

        cpu → host platform device; tpu/gpu → platform accelerator.  If the
        requested platform is unavailable (e.g. ``cpu(0)`` on a TPU-only
        axon tunnel, or ``tpu(0)`` in a CPU-only test run) we fall back to
        the default backend — reference scripts keep working either way.
        """
        dev_type = self.device_type
        if dev_type in ("cpu_pinned", "cpu_shared"):
            dev_type = "cpu"
        if dev_type == "gpu":  # alias: accelerator of the platform
            dev_type = _accelerator_platform()
        # multi-process: a context addresses THIS process's devices (the
        # reference's per-worker device numbering)
        try:
            devs = jax.local_devices(backend=dev_type)
        except RuntimeError:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Parity with Context.empty_cache; XLA manages HBM pools itself."""
        return None


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    """Source-compat alias: ``mx.gpu(i)`` targets the platform accelerator."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def _accelerator_platform() -> str:
    import os
    allowed = os.environ.get("JAX_PLATFORMS", "")
    allowed = [p.strip() for p in allowed.split(",") if p.strip()] or None
    for p in ("tpu", "gpu", "axon"):
        if allowed is not None and p not in allowed:
            continue
        try:
            if jax.devices(p):
                return p
        except RuntimeError:
            continue
    return "cpu"


def num_gpus() -> int:
    """Number of accelerator devices THIS process addresses (reference:
    mx.context.num_gpus — per-worker device count, matching jax_device's
    local resolution)."""
    plat = _accelerator_platform()
    if plat == "cpu":
        return 0
    return len(jax.local_devices(backend=plat))


def num_tpus() -> int:
    try:
        return len(jax.local_devices(backend="tpu"))
    except RuntimeError:
        return num_gpus()


def current_context() -> Context:
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is None:
        # default context is the accelerator if present, else cpu —
        # TPU-first: unlike the reference (cpu default), an available TPU
        # is the default compute device.
        ctx = cpu(0) if _accelerator_platform() == "cpu" else tpu(0)
        Context._default_ctx.value = ctx
    return ctx


Context.default_ctx = property(lambda self: current_context())
