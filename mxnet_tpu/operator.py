"""Custom operators implemented in Python (``mx.operator``).

Reference analog: ``python/mxnet/operator.py`` (CustomOp:426, CustomOpProp:
472, register:692) backed by ``src/operator/custom/custom.cc`` /
``custom-inl.h:50-173`` (N22): Python callbacks for infer-shape / forward /
backward, executed on a dedicated worker thread so host Python work never
blocks the scheduler.

TPU-native design: the ``Custom`` op lowers to ``jax.experimental.
io_callback(ordered=True)`` — the effectful, program-ordered XLA host
callback — wrapped in a ``jax.custom_vjp`` whose backward is a second
ordered callback into the user's ``backward``.  This works both in the
eager path and inside jitted CachedOp/Executor programs (the callback is a
host node in the compiled graph, the analog of the reference's kAsync custom
op dispatch).  User code still runs on one dedicated worker thread
(custom-inl.h:74-173 parity).  ``ordered=True`` is the structural fix for
the round-3 wedge: the runtime serializes the callbacks in program order on
the io-callback path instead of firing them from result-buffer completion
threads, so a callback re-entering jax eager dispatch (user ``mx.nd`` code)
can no longer interleave with another in-flight callback of the same
program; combined with the trace-time worker pre-warm this removed the
intermittent main<->worker futex deadlock (stress test:
tests/test_custom_op.py::test_custom_op_stress_in_process).
"""
from __future__ import annotations

import concurrent.futures
import functools
from typing import Dict, List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import register as _register_op, param

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_cls"]

# the reference executes all python custom-op callbacks on one dedicated
# worker thread (custom-inl.h:50-173); mirror that
import threading as _threading

_WORKER = concurrent.futures.ThreadPoolExecutor(
    max_workers=1, thread_name_prefix="mxnet_custom_op")
_WORKER_WARM = False
_WORKER_LOCK = _threading.Lock()
# the future a timed-out wait abandoned; while its thread is still
# RUNNING no new user callback may start (single-worker serialization,
# custom-inl.h parity) — submissions fail fast instead
_WEDGED_FUT = None


def _warm_body():
    from . import ndarray as nd
    nd.array(np.zeros((1,), np.float32)).asnumpy()


def _reset_worker(fut):
    """Abandon a wedged worker thread and start a fresh one: a timed-out
    callback cannot be cancelled (advisor r03), and without this every
    later Custom op would block the full timeout against the dead thread.
    The replacement is warmed immediately — cached compiled Custom ops
    skip the trace-time warm, and an unwarmed worker's first jax dispatch
    inside a host-callback context is the classic init race.  The
    abandoned future is remembered: until its thread actually finishes,
    new callbacks error fast rather than run CONCURRENTLY with it (the
    one-worker serialization guarantee must survive recovery)."""
    global _WORKER, _WEDGED_FUT
    with _WORKER_LOCK:
        old = _WORKER
        _WEDGED_FUT = fut
        _WORKER = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="mxnet_custom_op")
        _WORKER.submit(_warm_body)      # async: don't block the error path
    old.shutdown(wait=False)


def _warm_worker():
    """Pre-warm the worker thread's jax dispatch path from a NORMAL
    python thread (trace time), before any XLA host callback exists.
    First-use lazy init (thread spawn + first eager dispatch in that
    thread) racing under a host-callback context is the prime suspect
    for the rare bridge wedge (docs/DEVIATIONS.md)."""
    global _WORKER_WARM
    if _WORKER_WARM:
        return
    _WORKER_WARM = True
    try:
        with _WORKER_LOCK:
            fut = _WORKER.submit(_warm_body)
        fut.result(timeout=60)
    except Exception:
        pass


def _on_worker(fn, *args):
    import os
    import threading
    if threading.current_thread().name.startswith("mxnet_custom_op"):
        # nested Custom op (an op whose forward invokes another Custom op):
        # run inline — re-submitting to the single worker would deadlock
        return fn(*args)
    # bounded wait: a wedged worker surfaces as a loud MXNetError instead
    # of an indefinite futex hang (the reference's engine would likewise
    # abort on a stuck callback rather than stall the scheduler)
    global _WEDGED_FUT
    timeout = float(os.environ.get("MXNET_CUSTOM_OP_TIMEOUT_SEC", "600"))
    with _WORKER_LOCK:
        # another waiter's _reset_worker may swap+shutdown concurrently;
        # the lock pins submit to the live executor
        if _WEDGED_FUT is not None:
            if _WEDGED_FUT.running():
                raise MXNetError(
                    "Custom-op worker is still executing a previously "
                    "timed-out callback; refusing to run a second user "
                    "callback concurrently (single-worker guarantee)")
            _WEDGED_FUT = None          # old thread finished — all clear
        fut = _WORKER.submit(fn, *args)
    try:
        return fut.result(timeout=timeout)
    except concurrent.futures.TimeoutError:
        fut.cancel()      # prune if not yet started; never run it late
        _reset_worker(fut)  # the stuck thread is unrecoverable — replace
        raise MXNetError(
            "Custom-op callback did not complete within %.0fs "
            "(MXNET_CUSTOM_OP_TIMEOUT_SEC): worker thread wedged or the "
            "callback deadlocked" % timeout)


class CustomOp:
    """Base class for operators implemented in Python
    (parity: operator.py:426)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Assign ``src`` to ``dst`` according to ``req``
        (parity: operator.py:463)."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp:
    """Base class for custom-op property classes
    (parity: operator.py:472)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


_PROPS: Dict[str, type] = {}


def register(reg_name):
    """Register a CustomOpProp subclass under ``op_type=reg_name``
    (parity: operator.py:692)."""

    def deco(prop_cls):
        _PROPS[reg_name] = prop_cls
        # drop caches so re-registration (notebook iteration) takes effect:
        # prop instances AND compiled Custom executables bake in the class
        _make_prop.cache_clear()
        from .ops.registry import OPS
        OPS["Custom"]._jit_cache.clear()
        return prop_cls

    return deco


def get_prop_cls(name):
    cls = _PROPS.get(name)
    if cls is None:
        raise MXNetError("custom op type %r is not registered (have %s)"
                         % (name, sorted(_PROPS)))
    return cls


@functools.lru_cache(maxsize=256)
def _make_prop(op_type: str, kwargs_items: Tuple[Tuple[str, str], ...]):
    cls = get_prop_cls(op_type)
    # reference passes all ctor kwargs as strings (custom.cc param protocol)
    return cls(**{k: v for k, v in kwargs_items})


def _prop_of(attrs):
    items = tuple(sorted((k, str(v)) for k, v in attrs.items()
                         if k not in ("op_type",) and not k.startswith("__")
                         and v is not None))
    return _make_prop(attrs["op_type"], items)


def _nd_list(np_arrays):
    from . import ndarray as nd
    return [nd.array(a) for a in np_arrays]


def _custom_num_outputs(attrs):
    prop = _prop_of(attrs)
    return len(prop.list_outputs()) + len(prop.list_auxiliary_states())


def _custom_num_visible(attrs):
    return len(_prop_of(attrs).list_outputs())


def _custom_aux_writeback(attrs):
    """Updated aux states trail the user outputs; write them back into the
    trailing (aux) inputs — how the reference's CustomOp aux mutation is
    expressed functionally on TPU."""
    prop = _prop_of(attrs)
    n_out = len(prop.list_outputs())
    n_args = len(prop.list_arguments())
    return {n_out + i: n_args + i
            for i in range(len(prop.list_auxiliary_states()))}


@_register_op("Custom", nin=-1, train_aware=True,
              nout=_custom_num_outputs,
              visible=_custom_num_visible,
              aux_writeback=_custom_aux_writeback,
              params={"op_type": param(str, None, required=True)})
def _custom(attrs, *inputs):
    """The Custom op: host-callback execution of user Python code."""
    from . import ndarray as nd
    _warm_worker()   # trace-time: worker + its jax path init OUTSIDE callbacks
    prop = _prop_of(attrs)
    n_args = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    n_out = len(prop.list_outputs())
    if len(inputs) != n_args + n_aux:
        raise MXNetError(
            "Custom op %r expects %d inputs (%d args + %d aux), got %d"
            % (attrs["op_type"], n_args + n_aux, n_args, n_aux, len(inputs)))
    in_shapes = [tuple(x.shape) for x in inputs[:n_args]]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    in_types = [np.dtype(x.dtype) for x in inputs[:n_args]]
    _, out_types, _ = prop.infer_type(in_types)
    out_avals = tuple(jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
                      for s, t in zip(out_shapes, out_types))
    aux_avals = tuple(jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype))
                      for x in inputs[n_args:])
    is_train = bool(attrs.get("__train__", False))

    def _run_forward(*np_ins):
        def work():
            op = prop.create_operator(None, in_shapes, in_types)
            in_data = _nd_list(np_ins[:n_args])
            aux = _nd_list(np_ins[n_args:])
            out_data = [nd.zeros(s, dtype=t)
                        for s, t in zip(out_shapes, out_types)]
            op.forward(is_train, ["write"] * n_out, in_data, out_data, aux)
            # aux mutations flow back as extra outputs (written back into
            # the caller's aux NDArrays by the dispatch layer)
            return tuple(o.asnumpy() for o in out_data) + \
                tuple(a.asnumpy() for a in aux)
        return _on_worker(work)

    def _run_backward(*np_all):
        # np_all = inputs..., aux..., saved forward outputs..., out_grads...
        def work():
            op = prop.create_operator(None, in_shapes, in_types)
            in_data = _nd_list(np_all[:n_args])
            aux = _nd_list(np_all[n_args:n_args + n_aux])
            out_data = _nd_list(np_all[n_args + n_aux:
                                       n_args + n_aux + n_out])
            grads_np = np_all[n_args + n_aux + n_out:]
            out_grad = _nd_list(grads_np)
            in_grad = [nd.zeros(s, dtype=t)
                       for s, t in zip(in_shapes, in_types)]
            op.backward(["write"] * n_args, out_grad, in_data, out_data,
                        in_grad, aux)
            return tuple(g.asnumpy() for g in in_grad)
        return _on_worker(work)

    from jax.experimental import io_callback

    @jax.custom_vjp
    def _apply(*xs):
        # ordered=True: program-order serialization of the host callbacks
        # (the structural fix for the r03 callback-interleaving wedge);
        # also guarantees the effectful user forward is never elided
        outs = io_callback(_run_forward, out_avals + aux_avals, *xs,
                           ordered=True)
        return tuple(outs)

    def _apply_fwd(*xs):
        outs = _apply(*xs)
        # save the ACTUAL forward outputs: backward must not re-run a
        # (possibly stochastic) user forward to reconstruct out_data
        return outs, (xs, outs[:n_out])

    def _apply_bwd(res, gs):
        xs, outs = res
        in_avals = tuple(jax.ShapeDtypeStruct(s, t)
                         for s, t in zip(in_shapes, in_types))
        grads = io_callback(_run_backward, in_avals, *xs, *outs,
                            *gs[:n_out], ordered=True)
        # aux inputs receive zero gradient
        aux_zero = tuple(jnp.zeros(x.shape, x.dtype) for x in xs[n_args:])
        return tuple(grads) + aux_zero

    _apply.defvjp(_apply_fwd, _apply_bwd)
    outs = _apply(*inputs)
    # outputs: user outputs first, then updated aux (picked up by
    # get_aux_writeback below)
    return outs if len(outs) > 1 else outs[0]
