"""Learning-rate schedulers (parity: python/mxnet/lr_scheduler.py:53-140 —
Factor/MultiFactor/Poly)."""
from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "WarmupScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01):
        super().__init__(base_lr)
        if step < 1:
            raise ValueError("step must be >= 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("lr clamped to %.2e", self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, base_lr=0.01):
        super().__init__(base_lr)
        if not all(step[i] < step[i + 1] for i in range(len(step) - 1)):
            raise ValueError("steps must be increasing")
        self.step = list(step)
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        while self.cur_step_ind < len(self.step) and \
                num_update > self.step[self.cur_step_ind]:
            self.count = self.step[self.cur_step_ind]
            self.cur_step_ind += 1
            self.base_lr *= self.factor
        return self.base_lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, pwr=2):
        super().__init__(base_lr)
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.power = pwr

    def __call__(self, num_update):
        if num_update <= self.max_update:
            self.base_lr = self.base_lr_orig * \
                (1.0 - num_update / self.max_update) ** self.power
        return self.base_lr


class CosineScheduler(LRScheduler):
    """Cosine decay (beyond-parity convenience used by bench recipes)."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0.0):
        super().__init__(base_lr)
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.final_lr = final_lr

    def __call__(self, num_update):
        if num_update <= self.max_update:
            frac = num_update / self.max_update
            self.base_lr = self.final_lr + (self.base_lr_orig - self.final_lr) \
                * (1 + math.cos(math.pi * frac)) / 2
        return self.base_lr


class WarmupScheduler(LRScheduler):
    """Linear warmup wrapping another scheduler."""

    def __init__(self, warmup_steps, scheduler: LRScheduler):
        super().__init__(scheduler.base_lr)
        self.warmup_steps = warmup_steps
        self.scheduler = scheduler

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.scheduler.base_lr * (num_update + 1) / self.warmup_steps
        return self.scheduler(num_update - self.warmup_steps)
