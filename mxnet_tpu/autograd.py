"""Imperative autograd: record scopes + tape + backward.

Reference analog: ``src/imperative/imperative.cc`` (``Imperative::{RecordOp,
MarkVariables,Backward}``), the ``AGInfo`` tape stamped on NNVM nodes
(``include/mxnet/imperative.h:42-66``), and the Python face
``python/mxnet/autograd.py`` (record/pause/train_mode/predict_mode scopes,
``backward``, ``grad``).

TPU-native design: instead of replaying an NNVM gradient graph, each recorded
op call captures a ``jax.vjp`` closure (per-op VJP, the FGradient analog);
``backward`` walks the tape in reverse topological order accumulating
cotangents.  The user API is identical: ``with autograd.record(): ...;
loss.backward(); x.grad``.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    st = _st()
    old, st.recording = st.recording, flag
    return old


def set_training(flag: bool) -> bool:
    st = _st()
    old, st.training = st.training, flag
    return old


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._old


def record(train_mode: bool = True) -> _Scope:
    """Scope: record ops for autograd (ref autograd.py:122)."""
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# --------------------------------------------------------------------------
# tape
# --------------------------------------------------------------------------
class TapeNode:
    """One recorded op call (the AGInfo analog)."""

    __slots__ = ("vjp_fn", "in_entries", "n_out", "op_name", "saved")

    def __init__(self, vjp_fn, in_entries, n_out, op_name):
        self.vjp_fn = vjp_fn
        # per op-input: (TapeNode, out_idx) | NDArray leaf-with-grad | None
        self.in_entries = in_entries
        self.n_out = n_out
        self.op_name = op_name


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (ref: Imperative::MarkVariables)."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._ag_leaf = True
        v._ag_entry = None  # reset any prior tape link


def _entry_of(arr):
    """Tape entry for an NDArray input: tape link, leaf, or None."""
    e = getattr(arr, "_ag_entry", None)
    if e is not None:
        return e
    if getattr(arr, "_ag_leaf", False):
        return arr
    return None


def record_op(op_name, vjp_fn, in_arrays, out_arrays):
    """Called by the dispatch layer for each op executed under record()."""
    entries = [_entry_of(a) for a in in_arrays]
    if all(e is None for e in entries):
        return
    node = TapeNode(vjp_fn, entries, len(out_arrays), op_name)
    for i, o in enumerate(out_arrays):
        o._ag_entry = (node, i)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables
    (ref: Imperative::Backward, imperative.cc:270-470)."""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    # per-leaf accumulation for THIS pass; grad_req applied at the end
    # (within one backward, contributions from multiple paths always sum —
    # reference semantics; grad_req governs behavior across backward calls)
    leaf_acc: Dict[int, Tuple[object, jax.Array]] = {}

    def _leaf_accumulate(arr, g):
        prev = leaf_acc.get(id(arr))
        leaf_acc[id(arr)] = (arr, g if prev is None else prev[1] + g)

    # seed cotangents
    cotangents: Dict[Tuple[int, int], jax.Array] = {}
    nodes: Dict[int, TapeNode] = {}
    roots: List[TapeNode] = []
    for h, hg in zip(heads, head_grads):
        entry = getattr(h, "_ag_entry", None)
        if entry is None:
            if getattr(h, "_ag_leaf", False):
                g = jnp.ones_like(h._data) if hg is None else hg._data
                _leaf_accumulate(h, g)
                continue
            raise MXNetError("cannot differentiate: head is not connected "
                             "to any recorded computation")
        node, idx = entry
        g = jnp.ones_like(h._data) if hg is None else hg._data
        key = (id(node), idx)
        cotangents[key] = cotangents.get(key, 0) + g
        nodes[id(node)] = node
        roots.append(node)

    # topological order over the tape DAG (iterative DFS postorder)
    order: List[TapeNode] = []
    visited = set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for e in node.in_entries:
            if isinstance(e, tuple):
                parent = e[0]
                if id(parent) not in visited:
                    stack.append((parent, False))

    # reverse-topological cotangent propagation
    for node in reversed(order):
        outs = []
        missing = True
        for i in range(node.n_out):
            g = cotangents.get((id(node), i))
            outs.append(g)
            if g is not None:
                missing = False
        if missing:
            continue
        outs = [g if g is not None else None for g in outs]
        if node.vjp_fn is None:
            raise MXNetError(
                "gradient graph has already been freed by a previous "
                "backward(); pass retain_graph=True to backward() if you "
                "need to differentiate through shared subgraphs twice")
        in_grads = node.vjp_fn(outs)
        for e, g in zip(node.in_entries, in_grads):
            if e is None or g is None:
                continue
            if isinstance(e, tuple):
                pnode, pidx = e
                key = (id(pnode), pidx)
                prev = cotangents.get(key)
                cotangents[key] = g if prev is None else prev + g
            else:  # leaf NDArray
                _leaf_accumulate(e, g)
        if not retain_graph:
            node.vjp_fn = None

    # apply grad_req once per leaf
    for arr, g in leaf_acc.values():
        req = getattr(arr, "_grad_req", "write")
        if req == "null" or arr._grad is None:
            continue
        if req == "add":
            arr._grad._data = arr._grad._data + g.astype(arr._grad.dtype)
        else:
            arr._grad._data = g.astype(arr._grad.dtype)

    if not retain_graph:
        for h in heads:
            if getattr(h, "_ag_entry", None) is not None:
                h._ag_entry = None


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Functional gradient API (ref autograd.py:grad).  Returns grads of
    heads w.r.t. variables without touching .grad buffers."""
    from .ndarray import ndarray as _nd
    if create_graph:
        raise MXNetError("create_graph=True (higher-order imperative grad) "
                         "is not supported; use hybridized blocks + jax.grad")
    single = not isinstance(variables, (list, tuple))
    if single:
        variables = [variables]
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", None),
              getattr(v, "_ag_leaf", False)) for v in variables]
    for v in variables:
        if not getattr(v, "_ag_leaf", False):
            raise MXNetError("variables passed to grad() must have been "
                             "marked (attach_grad) before recording")
        v._grad = _nd.zeros(v.shape, dtype=v.dtype, ctx=v.context)
        v._grad_req = "add"
    backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    out = [v._grad for v in variables]
    for v, (g, req, leaf) in zip(variables, saved):
        v._grad, v._grad_req, v._ag_leaf = g, req, leaf
    return out[0] if single else out


class Function:
    """Custom differentiable function (ref autograd.py:363 Function).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` using NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, array as _array
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn = self

            def vjp(cots):
                grads_in = fn.backward(*[
                    _array(c) if c is not None else None for c in cots])
                if not isinstance(grads_in, (list, tuple)):
                    grads_in = [grads_in]
                return [g._data if g is not None else None for g in grads_in]

            record_op(type(self).__name__, vjp, list(inputs), outs)
        return outs[0] if single else outs
