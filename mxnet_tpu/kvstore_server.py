"""Parameter server for ``dist_async`` (reference parity:
``src/kvstore/kvstore_dist_server.h`` + ``python/mxnet/kvstore_server.py``).

The reference's async mode (``kvstore_dist_server.h:262`` DataHandle with
``sync_mode_ == false``) applies every worker push to the stored weight
IMMEDIATELY — no aggregation window, no barrier — and answers pulls with
whatever the weight currently is; the update rule is a **pickled Python
optimizer** shipped from worker 0 (``kvstore_server.py:55``).  ``dist_sync``
on this framework rides XLA collectives over DCN instead (SURVEY.md §5.8),
so this server exists exactly for the async-SGD semantics XLA cannot
express: lock-free-style staleness-tolerant updates.

TPU-native design: host-resident parameters (numpy) behind a threaded TCP
server — the transport role ps-lite's ZMQ plays in the reference.  Device
compute stays on the workers; the server only runs the (tiny) optimizer
update per key, under a per-key lock.  Wire format: length-prefixed
pickles (a trusted-cluster protocol, like ps-lite's).

Role dispatch mirrors the reference launcher contract: a process started
with ``DMLC_ROLE=server`` calls :func:`run_server` (via
``kvstore.create('dist_async')``), serves until every worker disconnects
and a stop command arrives, then exits.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, Optional

import numpy as np

from .base import MXNetError

__all__ = ["KVStoreServer", "run_server", "ps_address"]


def ps_address():
    """(host, port) of the parameter server from the launcher env."""
    host = os.environ.get("MXNET_PS_URI",
                          os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"))
    port = os.environ.get("MXNET_PS_PORT")
    if port is None:
        raise MXNetError(
            "dist_async needs a parameter server address: set MXNET_PS_PORT"
            " (tools/launch.py -s 1 does this)")
    return host, int(port)


def send_msg(sock: socket.socket, obj: Any):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def recv_msg(sock: socket.socket):
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (n,) = struct.unpack("<Q", header)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return pickle.loads(payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class KVStoreServer:
    """The async parameter server.

    Commands (reference CommandType analogs, kvstore_dist_server.h:44-73):
    ``init`` (first writer wins — worker 0 initializes, later inits are
    ignored like the reference's repeated InitImpl), ``push`` (apply
    optimizer immediately; plain assignment when no optimizer is set),
    ``pull`` (current value), ``set_optimizer`` (pickled optimizer ->
    server-side Updater; kController), ``barrier`` (rendezvous of
    num_workers), ``stop`` (kStopServer).
    """

    def __init__(self, host="127.0.0.1", port=0, num_workers=1):
        self._store: Dict[str, np.ndarray] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._meta_lock = threading.Lock()
        self._updater = None
        self._num_workers = num_workers
        self._barrier_cond = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._stop = threading.Event()
        self.push_count = 0

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    msg = recv_msg(self.request)
                    if msg is None:
                        return
                    reply = outer._dispatch(msg)
                    send_msg(self.request, reply)
                    if msg[0] == "stop":
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ---- command handlers ----------------------------------------------
    def _lock_for(self, key):
        with self._meta_lock:
            lk = self._locks.get(key)
            if lk is None:
                lk = self._locks[key] = threading.Lock()
            return lk

    def _dispatch(self, msg):
        cmd = msg[0]
        try:
            if cmd == "init":
                _, key, arr = msg
                with self._lock_for(key):
                    # first writer wins (worker 0 initializes the PS)
                    if key not in self._store:
                        self._store[key] = np.array(arr, copy=True)
                return ("ok",)
            if cmd == "push":
                _, key, grad = msg
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("push before init: %r" % key)
                    if self._updater is None:
                        # reference default: aggregate==assign in async
                        # mode each push replaces the value
                        self._store[key] = np.array(grad, copy=True)
                    else:
                        self._apply(key, np.asarray(grad))
                with self._meta_lock:   # per-key locks don't cover this
                    self.push_count += 1
                return ("ok",)
            if cmd == "pull":
                _, key = msg
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("pull before init: %r" % key)
                    return ("ok", self._store[key].copy())
            if cmd == "set_optimizer":
                _, payload = msg
                from . import optimizer as opt
                with self._meta_lock:
                    # first optimizer wins: every rank's Module calls
                    # set_optimizer (module.py init_optimizer), and a
                    # straggler's arrival must not rebuild the Updater —
                    # that would wipe accumulated momentum mid-training
                    if self._updater is None:
                        self._updater = opt.get_updater(
                            pickle.loads(payload))
                return ("ok",)
            if cmd == "barrier":
                self._wait_barrier()
                return ("ok",)
            if cmd == "stop":
                self._stop.set()
                threading.Thread(target=self._server.shutdown,
                                 daemon=True).start()
                return ("ok",)
            return ("err", "unknown command %r" % (cmd,))
        except Exception as e:  # surface to the worker (reference: the
            return ("err", str(e))  # error string crosses the wire)

    def _apply(self, key, grad):
        """Server-side optimizer step on the stored weight (immediate
        apply — the async semantics XLA collectives can't express)."""
        from . import ndarray as nd
        w = nd.array(self._store[key])
        self._updater(key, nd.array(grad), w)
        self._store[key] = w.asnumpy()

    def _wait_barrier(self):
        with self._barrier_cond:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
            else:
                while self._barrier_gen == gen and not self._stop.is_set():
                    self._barrier_cond.wait(timeout=1.0)

    # ---- lifecycle ------------------------------------------------------
    def start(self):
        """Serve on a background thread (in-process embedding and tests)."""
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._server.serve_forever()

    def shutdown(self):
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()


def run_server():
    """Entry for a ``DMLC_ROLE=server`` process (reference
    ``KVStoreServer.run`` loop, kvstore_server.py:73): bind the launcher
    address, serve until a worker sends ``stop``."""
    host, port = ps_address()
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    # Bind narrowly by default (advisor r04: the wire protocol is a
    # trusted-cluster one, so don't expose all interfaces gratuitously).
    # The ADVERTISED address (DMLC_PS_ROOT_URI — what workers dial) may
    # not be assignable on this host under NAT/port-mapping, so the bind
    # host is a separate knob; set MXNET_PS_BIND_HOST="" to bind-all.
    bind_host = os.environ.get("MXNET_PS_BIND_HOST", host)
    server = KVStoreServer(host=bind_host, port=port,
                           num_workers=num_workers)
    server.serve_forever()
