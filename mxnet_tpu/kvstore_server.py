"""Parameter server for ``dist_async`` (reference parity:
``src/kvstore/kvstore_dist_server.h`` + ``python/mxnet/kvstore_server.py``).

The reference's async mode (``kvstore_dist_server.h:262`` DataHandle with
``sync_mode_ == false``) applies every worker push to the stored weight
IMMEDIATELY — no aggregation window, no barrier — and answers pulls with
whatever the weight currently is; the update rule is a **pickled Python
optimizer** shipped from worker 0 (``kvstore_server.py:55``).  ``dist_sync``
on this framework rides XLA collectives over DCN instead (SURVEY.md §5.8),
so this server exists exactly for the async-SGD semantics XLA cannot
express: lock-free-style staleness-tolerant updates.

TPU-native design: host-resident parameters (numpy) behind a threaded TCP
server — the transport role ps-lite's ZMQ plays in the reference.  Device
compute stays on the workers; the server only runs the (tiny) optimizer
update per key, under a per-key lock.

Wire format (round 5, advisor r04): length-prefixed frames carrying a
JSON header + raw binary blobs — tensors travel as (dtype, shape, bytes),
NOT pickles, so a reachable port no longer means arbitrary code execution
on message decode.  The one pickle left on the wire is the
``set_optimizer`` blob (reference kvstore_server.py:55 ships a pickled
optimizer by design); it is passed through as opaque bytes and unpickled
only server-side, documented trusted-cluster.

Row-sparse and compressed traffic (reference kvstore_dist.h:228-291 and
:336-359): ``push_rsp``/``pull_rows`` move only touched rows, and
``push_2bit`` carries the packed 2-bit wire form (16 codes/word) which
the server dequantizes before applying.

Role dispatch mirrors the reference launcher contract: a process started
with ``DMLC_ROLE=server`` calls :func:`run_server` (via
``kvstore.create('dist_async')``), serves until every worker disconnects
and a stop command arrives, then exits.
"""
from __future__ import annotations

import hashlib
import json
import re
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .base import MXNetError
from . import chaos as _chaos
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["KVStoreServer", "run_server", "ps_address",
           "send_msg", "recv_msg", "recv_msg_tc"]

# Frame errors count unconditionally (cold path — a malformed frame is
# exactly the event an operator wants visible even before opting into
# hot-path telemetry); request counters/latency are `enabled`-gated.
_FRAME_ERRORS = _telemetry.counter(
    "kvstore_frame_errors_total",
    "Malformed KVStore wire frames rejected by recv_msg")
_SRV_REQS = _telemetry.counter(
    "kvstore_server_requests_total",
    "Requests handled by the parameter server", ("cmd",))
_SRV_LAT = _telemetry.histogram(
    "kvstore_server_request_latency_seconds",
    "Parameter-server request handling latency", ("cmd",))
_SRV_REPLAYS = _telemetry.counter(
    "kvstore_server_replays_total",
    "Duplicate (already-applied) frames dropped by seq dedup", ("cmd",))
_SRV_SNAPSHOTS = _telemetry.counter(
    "kvstore_server_snapshots_total",
    "Durable key-table snapshots written by the parameter server")
_SRV_REHYDRATES = _telemetry.counter(
    "kvstore_server_rehydrates_total",
    "Parameter-server restarts that rehydrated durable state")


def ps_address():
    """(host, port) of the parameter server from the launcher env."""
    host = os.environ.get("MXNET_PS_URI",
                          os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"))
    port = os.environ.get("MXNET_PS_PORT")
    if port is None:
        raise MXNetError(
            "dist_async needs a parameter server address: set MXNET_PS_PORT"
            " (tools/launch.py -s 1 does this)")
    return host, int(port)


def _encode(obj, blobs):
    """Message element -> JSON-able header node; ndarray/bytes payloads go
    to the blob list (raw, not executable)."""
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        blobs.append(arr.tobytes())
        return {"__nd__": len(blobs) - 1, "dtype": arr.dtype.str,
                "shape": list(arr.shape)}
    if isinstance(obj, (bytes, bytearray)):
        blobs.append(bytes(obj))
        return {"__bytes__": len(blobs) - 1}
    if isinstance(obj, (list, tuple)):
        return [_encode(x, blobs) for x in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    raise MXNetError("kvstore wire: cannot encode %r" % type(obj))


def _blob_at(blobs, idx):
    if not isinstance(idx, int) or not 0 <= idx < len(blobs):
        raise MXNetError("kvstore wire: bad blob index %r" % (idx,))
    return blobs[idx]


def _decode(node, blobs):
    if isinstance(node, dict):
        if "__nd__" in node:
            raw = _blob_at(blobs, node["__nd__"])
            dt = np.dtype(str(node["dtype"]))
            arr = np.frombuffer(raw, dtype=dt)
            shape = tuple(int(d) for d in node["shape"])
            if arr.size != int(np.prod(shape, dtype=np.int64)):
                raise MXNetError("kvstore wire: blob size mismatch")
            return arr.reshape(shape)
        if "__bytes__" in node:
            return _blob_at(blobs, node["__bytes__"])
        raise MXNetError("kvstore wire: unknown header node")
    if isinstance(node, list):
        return [_decode(x, blobs) for x in node]
    return node


def _pack_payload(obj: Any, trace_ctx: Optional[dict] = None,
                  health_ctx: Optional[dict] = None,
                  seq_ctx: Optional[dict] = None) -> bytes:
    """Serialize a message to frame-payload bytes (everything after the
    outer ``<Q total>`` length prefix).  Shared by the socket send path and
    the server's durable snapshot/journal records, so durability reuses the
    wire format's loud-reject validation on load."""
    blobs: list = []
    node: Any = _encode(list(obj), blobs)
    if trace_ctx or health_ctx or seq_ctx:
        node = {"m": node}
        if trace_ctx:
            node["tc"] = dict(trace_ctx)
        if health_ctx:
            node["h"] = dict(health_ctx)
        if seq_ctx:
            node["q"] = dict(seq_ctx)
    header = json.dumps(node).encode()
    parts = [struct.pack("<I", len(header)), header,
             struct.pack("<I", len(blobs))]
    for b in blobs:
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)


def send_msg(sock: socket.socket, obj: Any, trace_ctx: Optional[dict] = None,
             health_ctx: Optional[dict] = None,
             seq_ctx: Optional[dict] = None):
    """Frame: <Q total><I header_len><header json><I nblobs>(<Q len><raw>)*

    Without ``trace_ctx``/``health_ctx``/``seq_ctx`` the header is the
    encoded message list — the original wire format, byte-identical.  With
    a trace context the header becomes ``{"m": <encoded list>, "tc":
    {"t": trace_id, "s": span_id}}`` so the receiving handler span can
    adopt the sender's trace (Dapper-style propagation); ``health_ctx``
    rides the same wrapper as ``"h": {"r": rank, "st": step_seconds}``
    feeding the server's per-worker straggler table; ``seq_ctx`` rides as
    ``"q": {"r": rank, "s": seq}`` so the server can drop replayed frames
    after a reconnect (at-most-once apply for non-idempotent pushes).  Old
    receivers never see the wrapper unless one of the contexts is on.

    This is also the chaos harness's wire choke point: under
    ``MXNET_CHAOS`` a frame may be dropped (never sent — the peer's
    deadline-aware recv times out), delayed, or corrupted in its header
    region (the receiver's framing validation rejects it loudly)."""
    payload = _pack_payload(obj, trace_ctx, health_ctx, seq_ctx)
    if _chaos.active():
        action = _chaos.wire_action()
        if action == "drop":
            return
        if action == "delay":
            time.sleep(_chaos.delay_seconds())
        elif action == "corrupt":
            payload = _chaos.corrupt(payload)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _frame_error(why):
    """Reject a malformed frame loudly: slicing past the payload end would
    silently truncate ``__bytes__`` blobs (and desync every frame after)."""
    _FRAME_ERRORS.inc()
    raise MXNetError("kvstore wire: %s" % why)


# ---- bucketed frames (push_bucket / pull_bucket) --------------------------
# a bucket coalesces many keys' dense traffic into ONE flat dtype-uniform
# blob; its metadata is attacker-controlled like any frame, so it gets the
# same reject-loudly treatment plus a payload cap (a single bucket frame
# must not be able to ask the server for unbounded memory)
MAX_BUCKET_BYTES_ENV = "MXNET_KVSTORE_MAX_BUCKET_BYTES"
DEFAULT_MAX_BUCKET_BYTES = 256 << 20
MAX_BUCKET_KEYS = 4096


def _max_bucket_bytes():
    try:
        return int(os.environ.get(MAX_BUCKET_BYTES_ENV,
                                  DEFAULT_MAX_BUCKET_BYTES))
    except ValueError:
        return DEFAULT_MAX_BUCKET_BYTES


def _check_bucket_meta(keys, shapes):
    if not isinstance(keys, (list, tuple)) or not keys or \
            len(keys) > MAX_BUCKET_KEYS or \
            not all(isinstance(k, str) for k in keys):
        _frame_error("bucket keys must be 1..%d strings" % MAX_BUCKET_KEYS)
    if not isinstance(shapes, (list, tuple)) or len(shapes) != len(keys):
        _frame_error("bucket has %s shapes for %d keys"
                     % (len(shapes) if isinstance(shapes, (list, tuple))
                        else "non-list", len(keys)))
    for s in shapes:
        if not isinstance(s, (list, tuple)) or \
                not all(isinstance(d, int) and d >= 0 for d in s):
            _frame_error("bucket shape %r malformed" % (s,))


def _split_bucket(keys, shapes, flat):
    """Validate a push_bucket frame and split the flat payload back into
    per-key views (read-only — callers must copy before storing)."""
    _check_bucket_meta(keys, shapes)
    if not isinstance(flat, np.ndarray) or flat.ndim != 1:
        _frame_error("bucket payload must be one flat array")
    cap = _max_bucket_bytes()
    if flat.nbytes > cap:
        _frame_error("bucket of %d bytes exceeds %s=%d"
                     % (flat.nbytes, MAX_BUCKET_BYTES_ENV, cap))
    counts, total = [], 0
    for s in shapes:
        n = 1
        for d in s:
            n *= int(d)
        counts.append(n)
        total += n
    if total != flat.size:
        _frame_error("bucket payload has %d values, shapes need %d"
                     % (flat.size, total))
    segs, off = [], 0
    for k, s, n in zip(keys, shapes, counts):
        segs.append((k, flat[off:off + n].reshape([int(d) for d in s])))
        off += n
    return segs


# trace-context bounds: ids are "<pid-hex>.<seq-hex>" strings, far under
# this cap — anything larger/unknown is a malformed frame, not data
_TC_KEYS = frozenset(("t", "s"))
_TC_MAX_LEN = 64


def _check_trace_ctx(tc):
    """Validate an incoming wire trace context with the same loud-reject
    discipline as the framing bounds checks above."""
    if not isinstance(tc, dict):
        _frame_error("trace context is not an object")
    unknown = set(tc) - _TC_KEYS
    if unknown:
        _frame_error("unknown trace-context keys %s" % sorted(unknown))
    if set(tc) != _TC_KEYS:
        _frame_error("trace context missing fields")
    for k, v in tc.items():
        if not isinstance(v, str) or not v or len(v) > _TC_MAX_LEN:
            _frame_error("trace-context field %r malformed or oversized" % k)
    return tc


# health-context bounds: rank is a small decimal string, step time a
# non-negative finite number — anything else is a malformed frame
_HC_KEYS = frozenset(("r", "st"))
_HC_MAX_RANK_LEN = 16
_HC_MAX_STEP_SECONDS = 1e6


def _check_health_ctx(hc):
    """Validate an incoming wire health context (loud-reject, like the
    trace context and bucket metadata above)."""
    if not isinstance(hc, dict):
        _frame_error("health context is not an object")
    unknown = set(hc) - _HC_KEYS
    if unknown:
        _frame_error("unknown health-context keys %s" % sorted(unknown))
    if set(hc) != _HC_KEYS:
        _frame_error("health context missing fields")
    r = hc["r"]
    if not isinstance(r, str) or not r or len(r) > _HC_MAX_RANK_LEN \
            or not r.isdigit():
        _frame_error("health-context rank %r malformed" % (r,))
    st = hc["st"]
    if not isinstance(st, (int, float)) or isinstance(st, bool) \
            or not (0.0 <= float(st) < _HC_MAX_STEP_SECONDS):
        _frame_error("health-context step time %r out of bounds" % (st,))
    return {"r": r, "st": float(st)}


# sequence-context bounds: rank is a small decimal string (same shape as
# the health-context rank), seq a non-negative integer — anything else is
# a malformed frame
_QC_KEYS = frozenset(("r", "s"))
_QC_MAX_SEQ = 2 ** 62
#: worker identity on the wire: "<rank>" or "<rank>.<incarnation-hex>" —
#: the suffix gives every worker PROCESS its own dedup lane, so a
#: relaunched worker (seq restarts at 0) is never shadowed by the seqs a
#: rehydrated server remembers from its previous life
_QC_IDENT_RE = re.compile(r"^\d+(\.[0-9a-f]{1,16})?$")
_QC_MAX_IDENT_LEN = 33


def _check_seq_ctx(qc):
    """Validate an incoming wire sequence context (loud-reject, like the
    trace/health contexts and bucket metadata above)."""
    if not isinstance(qc, dict):
        _frame_error("seq context is not an object")
    unknown = set(qc) - _QC_KEYS
    if unknown:
        _frame_error("unknown seq-context keys %s" % sorted(unknown))
    if set(qc) != _QC_KEYS:
        _frame_error("seq context missing fields")
    r = qc["r"]
    if not isinstance(r, str) or not r or len(r) > _QC_MAX_IDENT_LEN \
            or not _QC_IDENT_RE.match(r):
        _frame_error("seq-context rank %r malformed" % (r,))
    s = qc["s"]
    if not isinstance(s, int) or isinstance(s, bool) \
            or not (0 <= s < _QC_MAX_SEQ):
        _frame_error("seq-context seq %r out of bounds" % (s,))
    return {"r": r, "s": s}


def _parse_payload(payload: bytes):
    """Parse frame-payload bytes into ``(msg, tc, hc, qc)`` with the full
    loud-reject validation.  Shared by the socket recv path and the
    durable snapshot/journal loader."""
    if len(payload) < 4:
        _frame_error("frame shorter than its header-length field")
    (hlen,) = struct.unpack_from("<I", payload, 0)
    if 4 + hlen + 4 > len(payload):
        _frame_error("header length %d overruns %d-byte frame"
                     % (hlen, len(payload)))
    try:
        hdr = json.loads(payload[4:4 + hlen].decode())
    except ValueError:
        _frame_error("header is not valid JSON")
    tc = hc = qc = None
    if isinstance(hdr, dict):
        # wrapped framing: {"m": message, "tc": {...}, "h": {...},
        # "q": {...}} — the message list itself is always a JSON array at
        # top level, so a dict here can only be the context wrapper
        unknown = set(hdr) - {"m", "tc", "h", "q"}
        if unknown:
            _frame_error("unknown header keys %s" % sorted(unknown))
        if "m" not in hdr:
            _frame_error("traced header missing message body")
        if hdr.get("tc") is not None:
            tc = _check_trace_ctx(hdr["tc"])
        if hdr.get("h") is not None:
            hc = _check_health_ctx(hdr["h"])
        if hdr.get("q") is not None:
            qc = _check_seq_ctx(hdr["q"])
        hdr = hdr["m"]
    off = 4 + hlen
    (nblobs,) = struct.unpack_from("<I", payload, off)
    off += 4
    blobs = []
    for _ in range(nblobs):
        if off + 8 > len(payload):
            _frame_error("blob length field overruns %d-byte frame"
                         % len(payload))
        (blen,) = struct.unpack_from("<Q", payload, off)
        off += 8
        if off + blen > len(payload):
            _frame_error("blob of %d bytes overruns %d-byte frame"
                         % (blen, len(payload)))
        blobs.append(payload[off:off + blen])
        off += blen
    if off != len(payload):
        _frame_error("%d trailing bytes after last blob"
                     % (len(payload) - off))
    return _decode(hdr, blobs), tc, hc, qc


def recv_msg_full(sock: socket.socket):
    """Receive one message plus its optional trace, health, and sequence
    contexts.

    Returns ``(msg, tc, hc, qc)`` where ``tc`` is ``{"t":..., "s":...}``
    or None, ``hc`` is ``{"r":..., "st":...}`` or None, and ``qc`` is
    ``{"r":..., "s":...}`` or None (old-format frames, whose header is the
    bare message list, keep parsing unchanged), or None on clean EOF."""
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (n,) = struct.unpack("<Q", header)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return _parse_payload(payload)


def recv_msg_tc(sock: socket.socket):
    """Receive one message plus its optional trace context — the original
    2-tuple API (existing callers and tests rely on its shape); any health
    context on the frame is validated then dropped."""
    got = recv_msg_full(sock)
    return None if got is None else (got[0], got[1])


def recv_msg(sock: socket.socket):
    """Receive one message, dropping any trace context (original API)."""
    got = recv_msg_full(sock)
    return None if got is None else got[0]


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class KVStoreServer:
    """The async parameter server.

    Commands (reference CommandType analogs, kvstore_dist_server.h:44-73):
    ``init`` (first writer wins — worker 0 initializes, later inits are
    ignored like the reference's repeated InitImpl), ``push`` (apply
    optimizer immediately; plain assignment when no optimizer is set),
    ``pull`` (current value), ``set_optimizer`` (pickled optimizer ->
    server-side Updater; kController), ``barrier`` (rendezvous of
    num_workers), ``stop`` (kStopServer).
    """

    #: durable snapshot magic (format version 1; program_cache's MXPC1
    #: pattern: magic + sha256 + payload, atomic tmp+replace writes)
    SNAP_MAGIC = b"MXKVS1\0"
    JOURNAL_MAGIC = b"MXKVJ1\0"

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 durable_dir: Optional[str] = None):
        self._store: Dict[str, np.ndarray] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._meta_lock = threading.Lock()
        self._updater = None
        self._opt_blob: Optional[bytes] = None
        self._num_workers = num_workers
        self._barrier_cond = threading.Condition()
        self._barrier_count = 0
        self._barrier_ranks: set = set()
        self._barrier_gen = 0
        self._stop = threading.Event()
        self.push_count = 0
        # per-worker last applied sequence number: a frame replayed after
        # a reconnect (same rank, seq <= applied) is acked without being
        # re-applied, making the retry path at-most-once for pushes
        self._applied_seq: Dict[str, int] = {}
        self._straggler_streak: Dict[str, int] = {}
        self._durable_dir = durable_dir
        self._durable_lock = threading.Lock()
        self._pushes_since_snap = 0
        if durable_dir:
            os.makedirs(durable_dir, exist_ok=True)
            self._rehydrate()

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        got = recv_msg_full(self.request)
                    except Exception as e:
                        # a malformed frame (old wire format, framing bug,
                        # bad blob index) answers with a diagnostic instead
                        # of silently killing the connection; the stream
                        # may be desynced after this, so close it
                        try:
                            send_msg(self.request,
                                     ("err", "bad frame: %s" % e))
                        except Exception:
                            pass
                        return
                    if got is None:
                        return
                    msg, tc, hc, qc = got
                    if hc is not None:
                        # worker-reported step time -> straggler table
                        # (the worker only attaches it when ITS health
                        # monitor is on, so no server-side gate needed)
                        from . import health as _health
                        _health.workers.update(hc["r"], hc["st"])
                        outer._maybe_escalate_straggler(hc["r"])
                    if _tracing.enabled:
                        # adopt the worker's trace context: the handler
                        # span joins the pushing span's trace and ends
                        # its cross-process flow
                        with _tracing.server_span(
                                "Server::%s" % (msg[0],), tc):
                            reply = self._timed_dispatch(msg, qc)
                    else:
                        reply = self._timed_dispatch(msg, qc)
                    send_msg(self.request, reply)
                    if msg[0] == "stop":
                        return

            def _timed_dispatch(self, msg, qc=None):
                if not _telemetry.enabled:
                    return outer._dispatch(msg, qc)
                t0 = time.perf_counter()
                reply = outer._dispatch(msg, qc)
                cmd = str(msg[0])
                _SRV_REQS.labels(cmd=cmd).inc()
                _SRV_LAT.labels(cmd=cmd).observe(time.perf_counter() - t0)
                return reply

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ---- command handlers ----------------------------------------------
    def _lock_for(self, key):
        with self._meta_lock:
            lk = self._locks.get(key)
            if lk is None:
                lk = self._locks[key] = threading.Lock()
            return lk

    #: commands whose apply is NOT naturally idempotent: replaying one
    #: after a reconnect must be acked without re-applying (a re-applied
    #: push would run the optimizer update twice; a re-joined barrier
    #: would double-count the rank)
    _MUTATING = frozenset(("push", "push_bucket", "push_rsp", "push_2bit",
                           "barrier"))

    def _dispatch(self, msg, qc=None):
        cmd = msg[0] if isinstance(msg, (list, tuple)) and msg else None
        if qc is not None and cmd in self._MUTATING:
            with self._meta_lock:
                done = qc["s"] <= self._applied_seq.get(qc["r"], -1)
            if done:
                # the op was applied but its ack was lost to the failure
                # the client is retrying around — ack, don't re-apply
                _SRV_REPLAYS.labels(cmd=str(cmd)).inc()
                return ("ok",)
        reply = self._dispatch_cmd(msg, qc)
        applied = isinstance(reply, tuple) and reply and reply[0] == "ok"
        if applied and qc is not None and cmd in self._MUTATING:
            with self._meta_lock:
                self._applied_seq[qc["r"]] = qc["s"]
        if applied and cmd in ("push", "push_bucket", "push_rsp",
                               "push_2bit"):
            self._maybe_snapshot()
            _chaos.server_push(self.push_count)
        return reply

    def _dispatch_cmd(self, msg, qc=None):
        cmd = msg[0]
        try:
            if cmd == "init":
                _, key, arr = msg
                with self._lock_for(key):
                    # first writer wins (worker 0 initializes the PS)
                    if key not in self._store:
                        self._store[key] = np.array(arr, copy=True)
                        self._journal(("init", key, self._store[key]))
                return ("ok",)
            if cmd == "push":
                _, key, grad = msg
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("push before init: %r" % key)
                    if self._updater is None:
                        # reference default: aggregate==assign in async
                        # mode each push replaces the value
                        self._store[key] = np.array(grad, copy=True)
                    else:
                        self._apply(key, np.asarray(grad))
                with self._meta_lock:   # per-key locks don't cover this
                    self.push_count += 1
                return ("ok",)
            if cmd == "pull":
                _, key = msg
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("pull before init: %r" % key)
                    return ("ok", self._store[key].copy())
            if cmd == "push_bucket":
                # coalesced dense push: several keys' gradients travel as
                # ONE flat dtype-uniform blob (O(params) -> O(buckets)
                # messages); semantics per key identical to "push"
                _, keys, shapes, flat = msg
                segs = _split_bucket(keys, shapes, np.asarray(flat))
                for key, seg in segs:
                    with self._lock_for(key):
                        if key not in self._store:
                            raise MXNetError("push before init: %r" % key)
                        if self._updater is None:
                            self._store[key] = np.array(seg, copy=True)
                        else:
                            self._apply(key, np.asarray(seg))
                with self._meta_lock:
                    self.push_count += len(segs)
                return ("ok",)
            if cmd == "pull_bucket":
                # coalesced dense pull: reply is ONE flat array in the
                # requested dtype, keys' values back-to-back in key order
                _, keys, shapes, dtstr = msg
                _check_bucket_meta(keys, shapes)
                dt = np.dtype(str(dtstr))
                budget = 0
                parts = []
                for key, shape in zip(keys, shapes):
                    with self._lock_for(key):
                        if key not in self._store:
                            raise MXNetError("pull before init: %r" % key)
                        w = self._store[key]
                        if list(w.shape) != [int(d) for d in shape]:
                            _frame_error(
                                "pull_bucket shape %r does not match "
                                "stored %r for key %r"
                                % (list(shape), list(w.shape), key))
                        part = np.ascontiguousarray(w, dtype=dt).ravel()
                    budget += part.nbytes
                    if budget > _max_bucket_bytes():
                        _frame_error(
                            "pull_bucket reply exceeds %s=%d"
                            % (MAX_BUCKET_BYTES_ENV, _max_bucket_bytes()))
                    parts.append(part)
                return ("ok", np.concatenate(parts))
            if cmd == "push_rsp":
                # row-sparse push: only touched (ids, rows) cross the wire
                # (reference kvstore_dist.h:228-291 RowSparse push)
                _, key, ids, rows = msg
                ids = np.asarray(ids, np.int64)
                rows = np.asarray(rows)
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("push before init: %r" % key)
                    if rows.shape[1:] != self._store[key].shape[1:] or \
                            len(ids) != len(rows):
                        raise MXNetError("push_rsp: shape mismatch")
                    if self._updater is None:
                        self._store[key][ids] = rows
                    else:
                        self._apply_rows(key, ids, rows)
                with self._meta_lock:
                    self.push_count += 1
                return ("ok",)
            if cmd == "pull_rows":
                # row_sparse_pull: answer with just the requested rows
                _, key, ids = msg
                ids = np.asarray(ids, np.int64)
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("pull before init: %r" % key)
                    # advanced indexing already copies
                    return ("ok", self._store[key][ids])
            if cmd == "push_2bit":
                # packed 2-bit gradient (16 codes/uint32 word); the server
                # dequantizes then applies (reference kvstore_dist.h:336)
                _, key, words, threshold = msg
                from .kvstore_compression import GradientCompression
                with self._lock_for(key):
                    if key not in self._store:
                        raise MXNetError("push before init: %r" % key)
                    w = self._store[key]
                    grad = GradientCompression.unpack(
                        np.asarray(words, np.uint32), w.size,
                        float(threshold), w.dtype).reshape(w.shape)
                    if self._updater is None:
                        self._store[key] = grad
                    else:
                        self._apply(key, grad)
                with self._meta_lock:
                    self.push_count += 1
                return ("ok",)
            if cmd == "set_optimizer":
                _, payload = msg
                from . import optimizer as opt
                with self._meta_lock:
                    # first optimizer wins: every rank's Module calls
                    # set_optimizer (module.py init_optimizer), and a
                    # straggler's arrival must not rebuild the Updater —
                    # that would wipe accumulated momentum mid-training
                    if self._updater is None:
                        self._updater = opt.get_updater(
                            pickle.loads(payload))
                        self._opt_blob = bytes(payload)
                        self._journal(("set_optimizer", bytes(payload)))
                return ("ok",)
            if cmd == "barrier":
                self._wait_barrier(rank=qc["r"] if qc else None)
                return ("ok",)
            if cmd == "stop":
                self._stop.set()
                threading.Thread(target=self._server.shutdown,
                                 daemon=True).start()
                return ("ok",)
            return ("err", "unknown command %r" % (cmd,))
        except Exception as e:  # surface to the worker (reference: the
            return ("err", str(e))  # error string crosses the wire)

    def _apply(self, key, grad):
        """Server-side optimizer step on the stored weight (immediate
        apply — the async semantics XLA collectives can't express)."""
        from . import ndarray as nd
        w = nd.array(self._store[key])
        self._updater(key, nd.array(grad), w)
        self._store[key] = w.asnumpy()

    def _apply_rows(self, key, ids, rows):
        """Row-sparse optimizer step: the updater sees a RowSparseNDArray
        gradient, so lazy-update optimizers (SGD/adagrad sparse paths)
        touch only the pushed rows (reference kvstore_dist_server.h
        ApplyUpdates on kRowSparsePushPull)."""
        from . import ndarray as nd
        from .ndarray.sparse import row_sparse_array
        w = nd.array(self._store[key])
        g = row_sparse_array((nd.array(rows), ids),
                             shape=self._store[key].shape)
        self._updater(key, g, w)
        self._store[key] = w.asnumpy()

    def _wait_barrier(self, rank=None):
        with self._barrier_cond:
            gen = self._barrier_gen
            if rank is None:
                self._barrier_count += 1
            else:
                # rank-keyed membership: a retried barrier frame (its
                # original handler thread may still be parked here) must
                # not count the same worker twice and release early.  The
                # identity carries an incarnation suffix ("0.ab12cd34")
                # so only the rank part counts — a relaunched worker must
                # not be mistaken for a second gang member
                self._barrier_ranks.add(str(rank).split(".", 1)[0])
            if self._barrier_count + len(self._barrier_ranks) \
                    >= self._num_workers:
                self._barrier_count = 0
                self._barrier_ranks.clear()
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
            else:
                while self._barrier_gen == gen and not self._stop.is_set():
                    self._barrier_cond.wait(timeout=1.0)

    # ---- durability ------------------------------------------------------
    # The key table is the only training state the gang cannot recompute:
    # a restarted server that comes back empty silently resets every
    # weight to its init.  Layout under durable_dir:
    #   snapshot.bin  MAGIC + sha256(payload) + payload   (atomic replace)
    #   journal.bin   MAGIC + (<Q len><sha256><payload>)* (append + fsync)
    # The journal holds only the rare structural records (init,
    # set_optimizer); the weight values themselves ride the periodic
    # snapshot, so a crash loses at most MXNET_KVSTORE_SNAPSHOT_EVERY
    # pushes of async-SGD progress — never keys, shapes, or the update
    # rule.  Replay after a snapshot load is first-writer-wins, so the
    # two sources compose without ordering bookkeeping.  ``_applied_seq``
    # rides the snapshot: it is copied BEFORE the weights, so a push that
    # races the snapshot boundary replays as at-least-once (benign for
    # async SGD) instead of being silently dropped.

    def _journal(self, record):
        if not self._durable_dir:
            return
        payload = _pack_payload(record)
        with self._durable_lock:
            path = os.path.join(self._durable_dir, "journal.bin")
            fresh = not os.path.exists(path)
            with open(path, "ab") as f:
                if fresh:
                    f.write(self.JOURNAL_MAGIC)
                f.write(struct.pack("<Q", len(payload)))
                f.write(hashlib.sha256(payload).digest())
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())

    def _maybe_snapshot(self):
        if not self._durable_dir:
            return
        try:
            every = int(os.environ.get("MXNET_KVSTORE_SNAPSHOT_EVERY",
                                       "100"))
        except ValueError:
            every = 100
        if every <= 0:
            return
        with self._meta_lock:
            self._pushes_since_snap += 1
            due = self._pushes_since_snap >= every
            if due:
                self._pushes_since_snap = 0
        if due:
            self.snapshot_now()

    def snapshot_now(self):
        """Write a checksummed snapshot of the full key table (atomic
        tmp+replace, program_cache-style).  Returns the path, or None when
        durability is off."""
        if not self._durable_dir:
            return None
        with self._meta_lock:
            keys = sorted(self._store)
            seq_ranks = sorted(self._applied_seq)
            seq_vals = [int(self._applied_seq[r]) for r in seq_ranks]
            push_count = int(self.push_count)
            opt_blob = self._opt_blob
        arrays = []
        for k in keys:
            with self._lock_for(k):
                arrays.append(np.array(self._store[k], copy=True))
        payload = _pack_payload(("snap", list(keys), arrays,
                                 list(seq_ranks), seq_vals, push_count,
                                 opt_blob))
        blob = (self.SNAP_MAGIC + hashlib.sha256(payload).digest()
                + payload)
        path = os.path.join(self._durable_dir, "snapshot.bin")
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with self._durable_lock:
            try:
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        _SRV_SNAPSHOTS.inc()
        return path

    def _load_snapshot(self):
        path = os.path.join(self._durable_dir, "snapshot.bin")
        if not os.path.exists(path):
            return False
        try:
            with open(path, "rb") as f:
                raw = f.read()
            head = len(self.SNAP_MAGIC)
            if not raw.startswith(self.SNAP_MAGIC):
                raise MXNetError("snapshot magic mismatch")
            want = raw[head:head + 32]
            payload = raw[head + 32:]
            if hashlib.sha256(payload).digest() != want:
                raise MXNetError("snapshot checksum mismatch")
            msg = _parse_payload(payload)[0]
            if not (isinstance(msg, list) and len(msg) == 7
                    and msg[0] == "snap"):
                raise MXNetError("snapshot record malformed")
            _, keys, arrays, seq_ranks, seq_vals, push_count, opt_blob = msg
            if len(keys) != len(arrays) or len(seq_ranks) != len(seq_vals):
                raise MXNetError("snapshot record malformed")
            for k, a in zip(keys, arrays):
                if not isinstance(k, str) or not isinstance(a, np.ndarray):
                    raise MXNetError("snapshot entry malformed")
                self._store[k] = np.array(a, copy=True)
            for r, s in zip(seq_ranks, seq_vals):
                self._applied_seq[str(r)] = int(s)
            self.push_count = int(push_count)
            if opt_blob is not None and self._updater is None:
                self._set_updater_from_blob(bytes(opt_blob))
            return True
        except Exception:
            # quarantine like program_cache: a corrupt snapshot must not
            # wedge every future restart
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
            self._store.clear()
            self._applied_seq.clear()
            return False

    def _replay_journal(self):
        path = os.path.join(self._durable_dir, "journal.bin")
        if not os.path.exists(path):
            return 0
        applied = 0
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return 0
        if not raw.startswith(self.JOURNAL_MAGIC):
            return 0
        off = len(self.JOURNAL_MAGIC)
        while off + 8 + 32 <= len(raw):
            (n,) = struct.unpack_from("<Q", raw, off)
            want = raw[off + 8:off + 40]
            payload = raw[off + 40:off + 40 + n]
            if len(payload) != n or \
                    hashlib.sha256(payload).digest() != want:
                break  # truncated/corrupt tail: crash mid-append
            off += 40 + n
            try:
                rec = _parse_payload(payload)[0]
            except MXNetError:
                break
            if not (isinstance(rec, list) and rec):
                break
            if rec[0] == "init" and len(rec) == 3 and \
                    isinstance(rec[1], str) and \
                    isinstance(rec[2], np.ndarray):
                if rec[1] not in self._store:  # snapshot wins
                    self._store[rec[1]] = np.array(rec[2], copy=True)
                    applied += 1
            elif rec[0] == "set_optimizer" and len(rec) == 2:
                if self._updater is None:
                    self._set_updater_from_blob(bytes(rec[1]))
                    applied += 1
        return applied

    def _set_updater_from_blob(self, blob):
        from . import optimizer as opt
        self._updater = opt.get_updater(pickle.loads(blob))
        self._opt_blob = blob

    def _rehydrate(self):
        """Restart path: snapshot first (bulk state), then journal replay
        (structural records since the last snapshot; first-writer-wins
        keeps the two composable in either order)."""
        snap = self._load_snapshot()
        replayed = self._replay_journal()
        if snap or replayed:
            _SRV_REHYDRATES.inc()
            try:
                from . import runlog as _runlog
                _runlog.event("kvstore_rehydrate", keys=len(self._store),
                              ranks={r: s for r, s in
                                     self._applied_seq.items()},
                              push_count=int(self.push_count),
                              from_snapshot=bool(snap),
                              journal_records=int(replayed))
            except Exception:
                pass

    def _maybe_escalate_straggler(self, rank):
        """PR 7 exported a straggler verdict; nothing consumed it.  After
        ``MXNET_HEALTH_STRAGGLER_GRACE`` consecutive straggler verdicts for
        a rank, snapshot and exit nonzero so ElasticRunner relaunches the
        gang (a persistently slow worker drags every barrier and async
        epoch; a gang restart re-places it)."""
        try:
            grace = int(os.environ.get("MXNET_HEALTH_STRAGGLER_GRACE",
                                       "0") or 0)
        except ValueError:
            grace = 0
        if grace <= 0:
            return
        from . import health as _health
        verdict = _health.workers.snapshot().get(str(rank), {})
        with self._meta_lock:
            if verdict.get("straggler"):
                streak = self._straggler_streak.get(str(rank), 0) + 1
            else:
                streak = 0
            self._straggler_streak[str(rank)] = streak
        if streak < grace:
            return
        try:
            from . import runlog as _runlog
            _runlog.event("straggler_escalation", worker_rank=str(rank),
                          streak=streak, grace=grace)
        except Exception:
            pass
        self.snapshot_now()
        os._exit(3)

    # ---- lifecycle ------------------------------------------------------
    def start(self):
        """Serve on a background thread (in-process embedding and tests)."""
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self):
        self._server.serve_forever()

    def shutdown(self):
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()


def run_server():
    """Entry for a ``DMLC_ROLE=server`` process (reference
    ``KVStoreServer.run`` loop, kvstore_server.py:73): bind the launcher
    address, serve until a worker sends ``stop``."""
    host, port = ps_address()
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    # Bind narrowly by default (advisor r04: the wire protocol is a
    # trusted-cluster one, so don't expose all interfaces gratuitously).
    # The ADVERTISED address (DMLC_PS_ROOT_URI — what workers dial) may
    # not be assignable on this host under NAT/port-mapping, so the bind
    # host is a separate knob; set MXNET_PS_BIND_HOST="" to bind-all.
    bind_host = os.environ.get("MXNET_PS_BIND_HOST", host)
    if _tracing.enabled:
        # collect handler spans for the whole serving lifetime, dumped
        # rank/role-keyed for tools/merge_traces.py when the stop command
        # shuts the server down
        from . import profiler as _profiler
        _profiler.set_state("run")
    server = KVStoreServer(host=bind_host, port=port,
                           num_workers=num_workers,
                           durable_dir=os.environ.get(
                               "MXNET_KVSTORE_DURABLE_DIR") or None)
    server.serve_forever()
    # clean stop: persist the final key table so a relaunched gang (or a
    # later evaluation run) starts from the last weights, not the last
    # periodic snapshot
    try:
        server.snapshot_now()
    except Exception:
        pass
    snap_path = os.environ.get("MXNET_HEALTH_SNAPSHOT_PATH")
    if snap_path:
        # shutdown evidence for the launcher/tests: the aggregated
        # per-worker step table with straggler verdicts (same pattern as
        # the trace dump below)
        from . import health as _health
        try:
            with open(snap_path, "w") as f:
                json.dump({"workers": _health.workers.snapshot()}, f)
        except OSError:
            pass
    try:
        # ledger epilogue: the final straggler table, then run_end
        from . import health as _health
        from . import runlog as _runlog
        if _runlog.enabled():
            _runlog.event("straggler_table",
                          workers=_health.workers.snapshot())
            _runlog.disable()
    except Exception:
        pass
    if _tracing.enabled:
        _tracing.dump_process_trace(role="server")
