"""Data iterators (parity: python/mxnet/io.py — DataIter:182, NDArrayIter:546,
PrefetchingIter:349, ResizeIter; plus the registered C++ iterators of
src/io/ (SURVEY.md N14): MNISTIter, CSVIter, ImageRecordIter).

TPU-native design: the reference's C++ decode/augment thread pool +
``dmlc::ThreadedIter`` double buffering maps onto the host dependency engine
(``mxnet_tpu.engine``): PrefetchingIter pushes batch production as engine ops
so host IO overlaps device compute; device transfer happens once per batch
(``device_put``) feeding the XLA pipeline.
"""
from __future__ import annotations

import os
import gzip
import queue
import struct
import threading
import time
from collections import deque, namedtuple
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import telemetry as _telemetry
from . import health as _health
from .ndarray.ndarray import NDArray

_IO_BATCHES = _telemetry.counter(
    "io_batches_total", "Batches produced by data iterators", ("iter",))
_IO_WAIT = _telemetry.histogram(
    "io_prefetch_wait_seconds",
    "Consumer-side wait on the prefetch queue (0 when a batch was ready)",
    ("iter",))
_IO_WS = _telemetry.gauge(
    "io_workspace_bytes",
    "Pooled staging-workspace bytes held by the iterator", ("iter",))
_IO_PUT = _telemetry.histogram(
    "io_device_put_seconds",
    "Producer-side device placement (host->device upload) per batch",
    ("iter",))
_IO_DEPTH = _telemetry.gauge(
    "io_pipeline_depth",
    "Configured in-flight batch depth of the producer pipeline", ("iter",))
_IO_WORKERS = _telemetry.gauge(
    "io_pipeline_workers",
    "Worker threads producing batches for the pipeline", ("iter",))

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "SyntheticLMIter", "CSVIter", "MNISTIter", "PrefetchingIter",
           "ResizeIter", "ImageRecordIter", "LibSVMIter",
           "ImageDetRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), np.dtype(dtype), layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (ref io.py:DataIter).

    ``sharding`` is the mesh-training hook: a ``jax.sharding.Sharding``
    for the produced batch (typically ``NamedSharding(mesh, P('dp'))``).
    Iterators that honor it land batches on device pre-sharded, so the
    train step never pays a host→device placement on its critical path;
    see ``parallel.mesh.host_shard_hint`` for the multi-host
    ``(rank, nranks)`` counterpart.
    """

    def __init__(self, batch_size=0, sharding=None):
        self.batch_size = batch_size
        self.sharding = sharding

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            batch = DataBatch(self.getdata(), self.getlabel(),
                              pad=self.getpad(), index=self.getindex())
            if _telemetry.enabled:
                _IO_BATCHES.labels(iter=type(self).__name__).inc()
            return batch
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _part_rows(v, rank, nranks):
    """Contiguous row block of `v` for one of `nranks` loading hosts."""
    n = v.shape[0]
    return v[n * rank // nranks: n * (rank + 1) // nranks]


def _init_data(data, allow_empty, default_name):
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise MXNetError("empty data")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {default_name + "_%d" % i: d for i, d in enumerate(data)}
    out = {}
    for k, v in dict(data).items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out[k] = np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """In-memory iterator (ref io.py:NDArrayIter): shuffle, pad/discard/
    roll_over last-batch handling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", num_parts=1, part_index=0,
                 sharding=None):
        super().__init__(batch_size, sharding=sharding)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        # per-host sharded loading (parallel.mesh.host_shard_hint): this
        # process keeps only its contiguous 1/num_parts row block, so a
        # multi-host mesh never decodes the full global batch per host
        if not 0 <= part_index < num_parts:
            raise MXNetError("part_index %d out of range for num_parts %d"
                             % (part_index, num_parts))
        self.num_parts = num_parts
        self.part_index = part_index
        if num_parts > 1:
            self.data = [(k, _part_rows(v, part_index, num_parts))
                         for k, v in self.data]
            self.label = [(k, _part_rows(v, part_index, num_parts))
                          for k, v in self.label]
        self.num_data = self.data[0][1].shape[0]
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = np.arange(self.num_data)
        self.cursor = -batch_size
        self._cache = None
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, v in arrays:
            start = self.cursor
            end = self.cursor + self.batch_size
            if end <= self.num_data:
                sel = self.idx[start:end]
            else:  # pad by wrapping
                sel = np.concatenate([self.idx[start:],
                                      self.idx[:end - self.num_data]])
            out.append(nd.array(v[sel], dtype=v.dtype))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class SyntheticLMIter(DataIter):
    """Deterministic synthetic next-token-prediction stream for LM
    workloads (models.transformer): data is ``(B, T)`` token ids, label
    is the same stream shifted one position (a REAL next-token target,
    not independent noise, so eval losses below ln(vocab) are
    achievable).  The full corpus is generated once from ``seed`` —
    identical across processes and runs, which is what makes bench
    rounds and multi-host parity tests reproducible without shipping a
    dataset.  ``num_parts``/``part_index`` follow the
    ``parallel.mesh.host_shard_hint`` contract (each host keeps its
    contiguous batch-row block)."""

    def __init__(self, vocab_size, seq_len, batch_size=1, num_batches=16,
                 seed=0, data_name="data", label_name="softmax_label",
                 dtype="float32", num_parts=1, part_index=0, sharding=None):
        super().__init__(batch_size, sharding=sharding)
        if not 0 <= part_index < num_parts:
            raise MXNetError("part_index %d out of range for num_parts %d"
                             % (part_index, num_parts))
        self.vocab_size = int(vocab_size)
        self.seq_len = int(seq_len)
        self.num_batches = int(num_batches)
        self.seed = int(seed)
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = np.dtype(dtype)
        rng = np.random.RandomState(self.seed)
        # one extra token so every position has a next-token label
        corpus = rng.randint(0, self.vocab_size,
                             size=self.num_batches * batch_size
                             * self.seq_len + 1)
        n = self.num_batches * batch_size * self.seq_len
        self._data = corpus[:n].reshape(
            self.num_batches, batch_size, self.seq_len).astype(self.dtype)
        self._label = corpus[1:n + 1].reshape(
            self.num_batches, batch_size, self.seq_len).astype(self.dtype)
        if num_parts > 1:
            if batch_size % num_parts:
                raise MXNetError("batch_size %d not divisible by "
                                 "num_parts %d" % (batch_size, num_parts))
            self.batch_size = batch_size // num_parts
            self._data = self._data[:, _part_slice(batch_size, part_index,
                                                   num_parts)]
            self._label = self._label[:, _part_slice(batch_size, part_index,
                                                     num_parts)]
        self.cursor = -1

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size, self.seq_len),
                         self.dtype, layout="NT")]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, (self.batch_size, self.seq_len),
                         self.dtype, layout="NT")]

    def reset(self):
        self.cursor = -1

    def iter_next(self):
        self.cursor += 1
        return self.cursor < self.num_batches

    def _batch_array(self, v):
        arr = nd.array(v[self.cursor], dtype=self.dtype)
        if self.sharding is not None:
            import jax
            arr._data = jax.device_put(arr._data, self.sharding)
        return arr

    def getdata(self):
        return [self._batch_array(self._data)]

    def getlabel(self):
        return [self._batch_array(self._label)]


def _part_slice(batch, rank, nranks):
    return slice(batch * rank // nranks, batch * (rank + 1) // nranks)


class CSVIter(DataIter):
    """CSV file iterator (ref src/io/iter_csv.cc:218)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.dtype(dtype),
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        else:
            label = np.zeros((data.shape[0],), np.float32)
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="pad"
                                  if round_batch else "discard",
                                  label_name="label")

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __next__(self):
        return self._inner.__next__()

    next = __next__

    def reset(self):
        self._inner.reset()


class MNISTIter(DataIter):
    """MNIST idx-format iterator (ref src/io/iter_mnist.cc:260).  Reads the
    standard (optionally gzipped) idx files."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        super().__init__(batch_size)
        imgs = _read_idx(image)
        labels = _read_idx(label)
        imgs = imgs.astype(np.float32) / 255.0
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, imgs.shape[1], imgs.shape[2])
        if input_shape is not None:
            imgs = imgs.reshape((imgs.shape[0],) + tuple(input_shape))
        self._inner = NDArrayIter(imgs, labels.astype(np.float32), batch_size,
                                  shuffle=shuffle, last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def __next__(self):
        return next(self._inner)

    next = __next__


def _read_idx(path):
    if not os.path.exists(path):
        for alt in (path + ".gz",):
            if os.path.exists(alt):
                path = alt
                break
        else:
            raise MXNetError("MNIST file not found: %s" % path)
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        buf = f.read()
    magic = struct.unpack(">I", buf[:4])[0]
    ndim = magic & 0xFF
    dims = struct.unpack(">" + "I" * ndim, buf[4:4 + 4 * ndim])
    data = np.frombuffer(buf, dtype=np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


class PrefetchingIter(DataIter):
    """Multi-worker background prefetch with device-side double buffering
    (ref io.py:349 + iter_prefetcher.h + the iter_image_recordio_2.cc
    worker pool).

    ``num_workers`` threads produce batches concurrently.  The underlying
    ``next(it)`` calls stay serialized under a fetch lock — inner
    iterators are not thread-safe and batch ORDER must match the
    unpipelined iterator exactly — while the expensive per-batch work
    (flattening plus, when ``sharding``/``device`` is set, the
    host->device ``jax.device_put``) runs outside the lock in parallel
    and is reassembled in sequence order before entering the bounded
    prefetch queue.  With a placement target the producer lands batch
    N+1 on device (pre-sharded against the cached ``NamedSharding`` for
    the mesh step, plain device placement otherwise) while the consumer
    computes step N, so the train step never pays the H2D copy on its
    critical path.

    ``prefetch_depth`` bounds in-flight batches (0 -> env
    ``MXNET_IO_PREFETCH_DEPTH``, default 2); ``num_workers`` defaults
    from ``MXNET_IO_PIPELINE_WORKERS`` falling back to
    ``MXNET_CPU_WORKER_NTHREADS``.
    """

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=0, sharding=None, device=None,
                 num_workers=0):
        if not isinstance(iters, list):
            iters = [iters]
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        super().__init__(iters[0].batch_size, sharding=sharding)
        if prefetch_depth <= 0:
            prefetch_depth = int(os.environ.get(
                "MXNET_IO_PREFETCH_DEPTH", "2"))
        self.prefetch_depth = max(1, prefetch_depth)
        if num_workers <= 0:
            num_workers = int(os.environ.get(
                "MXNET_IO_PIPELINE_WORKERS",
                os.environ.get("MXNET_CPU_WORKER_NTHREADS", "2")))
        self.num_workers = max(1, num_workers)
        self.device = device
        self._target = self._placement()
        self.current_batch = None
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=self.prefetch_depth)
        self._stop = threading.Event()
        self._fetch_lock = threading.Lock()
        self._emit_cv = threading.Condition()
        self._threads: List[threading.Thread] = []
        self._seq = 0
        self._next_emit = 0
        self._eof = False
        self._done = False
        self._start()

    def _placement(self):
        """Sharding the producer lands batches on (None = host batches)."""
        if self.sharding is not None:
            return self.sharding
        if self.device is None:
            return None
        from jax.sharding import SingleDeviceSharding
        dev = getattr(self.device, "jax_device", self.device)
        return SingleDeviceSharding(dev)

    @property
    def _label(self):
        return "PrefetchingIter.mesh" if self.sharding is not None \
            else "PrefetchingIter"

    @property
    def provide_data(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_data
            if self.rename_data:
                # keep ALL four DataDesc fields: dropping layout here
                # broke get_batch_axis for renamed non-NCHW inputs
                descs = [DataDesc(self.rename_data[i].get(d.name, d.name),
                                  d.shape, d.dtype, d.layout)
                         for d in descs]
            out.extend(descs)
        return out

    @property
    def provide_label(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_label
            if self.rename_label:
                descs = [DataDesc(self.rename_label[i].get(d.name, d.name),
                                  d.shape, d.dtype, d.layout)
                         for d in descs]
            out.extend(descs)
        return out

    def _put(self, item) -> bool:
        """Stop-aware put; returns False if reset() interrupted us."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _place(self, arr):
        """Land a batch array on the placement target (producer side), so
        the consumer-side step finds it already on device/pre-sharded."""
        import jax
        data = getattr(arr, "_data", None)
        if data is None:
            return arr
        if getattr(data, "sharding", None) != self._target:
            arr._data = jax.device_put(data, self._target)
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            # producer-side staging buffers: double-buffered batches live
            # on device before the consumer step adopts them
            _memwatch.tag("io", arr._data, detail=self._label)
        return arr

    def _assemble(self, batches):
        data = sum((b.data for b in batches), [])
        label = sum((b.label for b in batches), [])
        if self._target is not None:
            t0 = time.perf_counter()
            data = [self._place(a) for a in data]
            label = [self._place(a) for a in label]
            if _telemetry.enabled:
                _IO_PUT.labels(iter=self._label).observe(
                    time.perf_counter() - t0)
        return DataBatch(data, label, pad=batches[0].pad,
                         index=getattr(batches[0], "index", None))

    def _emit(self, seq, item) -> bool:
        """Ordered reassembly: deliver `item` as the seq-th queue entry."""
        with self._emit_cv:
            while self._next_emit != seq:
                if self._stop.is_set():
                    return False
                self._emit_cv.wait(timeout=0.05)
            ok = self._put(item)
            self._next_emit = seq + 1
            self._emit_cv.notify_all()
        return ok

    def _worker(self):
        while not self._stop.is_set():
            with self._fetch_lock:
                if self._eof:
                    return
                seq = self._seq
                self._seq += 1
                try:
                    batches = [next(it) for it in self.iters]
                except StopIteration:
                    self._eof = True
                    batches = None
                except Exception as e:  # surfaced on the consumer side
                    self._eof = True
                    batches = e
            if batches is None:
                self._emit(seq, None)
                return
            if isinstance(batches, Exception):
                self._emit(seq, batches)
                return
            try:
                item = self._assemble(batches)
            except Exception as e:  # surfaced on the consumer side
                with self._fetch_lock:
                    self._eof = True
                item = e
            if not self._emit(seq, item):
                return

    def _start(self):
        self._seq = 0
        self._next_emit = 0
        self._eof = False
        self._done = False
        if _telemetry.enabled:
            _IO_DEPTH.labels(iter=self._label).set(self.prefetch_depth)
            _IO_WORKERS.labels(iter=self._label).set(self.num_workers)
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name="prefetch-worker-%d" % i)
            for i in range(self.num_workers)]
        for t in self._threads:
            t.start()

    def reset(self):
        # stop the producers FIRST, then drain — otherwise an in-flight
        # batch lands after the drain and leaks into the next epoch
        self._stop.set()
        with self._emit_cv:
            self._emit_cv.notify_all()
        for t in self._threads:
            while t.is_alive():
                try:  # unblock a producer stuck in put on a full queue
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)
        while True:  # final drain after every producer has exited
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        for it in self.iters:
            it.reset()
        self.current_batch = None
        self._stop.clear()
        self._start()

    def _get_timed(self):
        """Queue get, measuring how long the consumer sat starved."""
        if not _telemetry.enabled:
            return self._queue.get()
        t0 = time.perf_counter()
        batch = self._queue.get()
        wait = time.perf_counter() - t0
        _IO_WAIT.labels(iter=self._label).observe(wait)
        if _health.enabled:
            _health.monitor.note_phase("input", wait)
        return batch

    def _consume(self):
        batch = self._get_timed()
        if batch is None:
            self._done = True
            return None
        if isinstance(batch, Exception):
            self._done = True
            raise batch
        if _telemetry.enabled:
            _IO_BATCHES.labels(iter="PrefetchingIter").inc()
        return batch

    def __next__(self):
        # honor a batch already fetched by iter_next() (reference
        # PrefetchingIter: iter_next fills current_batch, next returns it)
        if self.current_batch is not None:
            batch, self.current_batch = self.current_batch, None
            return batch
        if self._done:
            # post-EOF next() must re-raise, not block on an idle queue
            raise StopIteration
        batch = self._consume()
        if batch is None:
            raise StopIteration
        return batch

    next = __next__

    def iter_next(self):
        if self.current_batch is not None:
            return True
        if self._done:
            return False
        batch = self._consume()
        if batch is None:
            return False
        self.current_batch = batch
        return True

    def getdata(self):
        assert self.current_batch is not None, \
            "call iter_next() before getdata()"
        return self.current_batch.data

    def getlabel(self):
        assert self.current_batch is not None, \
            "call iter_next() before getlabel()"
        return self.current_batch.label

    def getindex(self):
        return getattr(self.current_batch, "index", None)

    def getpad(self):
        return getattr(self.current_batch, "pad", 0)


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (ref io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = next(self.data_iter)
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = next(self.data_iter)
        self.cur += 1
        return True

    def __next__(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    next = __next__

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class ImageRecordIter(DataIter):
    """RecordIO image iterator (ref src/io/iter_image_recordio_2.cc:727):
    multithreaded JPEG decode + augmentation feeding batches.

    Same pipeline shape as the reference's ImageRecordIOParser2: a reader
    walks the record file sequentially (cheap), ``preprocess_threads``
    workers JPEG-decode + augment concurrently (cv2/PIL release the GIL),
    and assembled batches wait in a bounded prefetch queue so decode
    overlaps the training step.  Up to ``prefetch_buffer`` BATCHES decode
    concurrently: workers write straight into a ring of reusable staging
    buffers and the producer reassembles them strictly in order, so the
    pool is never drained batch-by-batch.  Thread count honors the
    ``MXNET_CPU_WORKER_NTHREADS`` env (the reference's engine worker knob,
    docs/faq/env_var.md) with ``preprocess_threads`` as the per-iterator
    override.  ``num_parts``/``part_index`` shard the stream per mesh
    host (defaulting from ``parallel.mesh.host_shard_hint``) so
    multi-host training never decodes the full dataset on every host.
    The augmentation params mirror image_aug_default.cc (resize,
    rand_crop, rand_mirror, mean/std normalization)."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 resize=-1, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 preprocess_threads=0, prefetch_buffer=2, path_imgidx=None,
                 round_batch=True, data_name="data",
                 label_name="softmax_label", num_parts=None,
                 part_index=None, **kwargs):
        super().__init__(batch_size)
        from . import recordio
        self.data_shape = tuple(data_shape)
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)
        self.scale = scale
        self.shuffle = shuffle
        self.data_name = data_name
        self.label_name = label_name
        if preprocess_threads <= 0:
            preprocess_threads = int(os.environ.get(
                "MXNET_CPU_WORKER_NTHREADS", "4"))
        self._nthreads = max(1, preprocess_threads)
        self._prefetch = max(1, prefetch_buffer)
        self._pool = None
        self._queue = None
        self._producer_thread = None
        self._stop = threading.Event()
        self._mem = None
        # pipelined-producer state: up to `prefetch_buffer` batches decode
        # concurrently, each into its own slot of a reusable staging ring
        self._inflight = deque()
        self._bufs = None
        self._reader_done = False
        self._seq_read = 0
        # batch staging buffers come from the per-context temp-space pool
        # (resource.cc kTempSpace semantics: one rotating slot per user,
        # reused across batches instead of a fresh malloc per batch)
        from . import resource as _resource
        from . import context as _ctx
        self._workspace_res = _resource.ResourceManager.get().request(
            _ctx.cpu(0),
            _resource.ResourceRequest(_resource.ResourceRequest.kTempSpace))
        if path_imgidx and os.path.exists(path_imgidx):
            self.rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self.keys = list(self.rec.keys)
        else:
            self.rec = recordio.MXRecordIO(path_imgrec, "r")
            self.keys = None
            if shuffle:
                # no index for random access: load raw records into memory
                # so shuffling is real (the reference C++ iterator shuffles
                # chunk-wise; silent sequential order would be wrong)
                import warnings
                warnings.warn(
                    "ImageRecordIter: shuffle=True without path_imgidx "
                    "loads the whole .rec into memory; provide an .idx "
                    "file for large datasets")
                self._mem = []
                while True:
                    raw = self.rec.read()
                    if raw is None:
                        break
                    self._mem.append(raw)
        # per-host sharded loading: each mesh host keeps 1/num_parts of the
        # stream (defaults from parallel.mesh.host_shard_hint), so multi-
        # host training never re-decodes the full dataset on every host
        if num_parts is None and part_index is None:
            from .parallel.mesh import host_shard_hint
            part_index, num_parts = host_shard_hint()
        num_parts = 1 if num_parts is None else int(num_parts)
        part_index = 0 if part_index is None else int(part_index)
        if not 0 <= part_index < num_parts:
            raise MXNetError(
                "ImageRecordIter: part_index %d out of range for "
                "num_parts %d" % (part_index, num_parts))
        self.num_parts, self.part_index = num_parts, part_index
        if num_parts > 1:
            if self.keys is not None:
                n = len(self.keys)
                self.keys = self.keys[n * part_index // num_parts:
                                      n * (part_index + 1) // num_parts]
            elif self._mem is not None:
                n = len(self._mem)
                self._mem = self._mem[n * part_index // num_parts:
                                      n * (part_index + 1) // num_parts]
        self._order = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, (self.batch_size,))]

    def reset(self):
        self._stop_producer()
        if getattr(self, "_workspace_res", None) is None:
            # resuming after close(): reset() is the one sanctioned way to
            # bring the iterator back, so re-acquire the temp-space slot
            # (the pool is likewise rebuilt by _start_producer below)
            from . import resource as _resource
            from . import context as _ctx
            self._workspace_res = _resource.ResourceManager.get().request(
                _ctx.cpu(0), _resource.ResourceRequest(
                    _resource.ResourceRequest.kTempSpace))
        self.rec.reset()
        if self.keys is not None:
            self._order = list(self.keys)
            if self.shuffle:
                np.random.shuffle(self._order)
            self._pos = 0
        elif self._mem is not None:
            self._order = np.random.permutation(len(self._mem)).tolist()
            self._pos = 0
        self._seq_read = 0
        self._done = False
        self._start_producer()

    def close(self):
        self._stop_producer()
        if getattr(self, "_pool", None) is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        # release the staging ring and temp-space slot with the iterator,
        # not at GC time
        self._bufs = None
        self._workspace_res = None

    __del__ = close

    @property
    def _workspace(self):
        # close() releases the temp-space slot for good; only an explicit
        # reset() re-acquires it.  Lazily re-acquiring here would silently
        # resurrect a half-closed iterator (dead pool, no producer) the
        # first time anything touched the workspace.
        ws = self._workspace_res
        if ws is None:
            raise MXNetError(
                "ImageRecordIter: used after close(); call reset() to "
                "restart the iterator")
        return ws

    def _read_raw(self):
        """Sequential record read (reader stage of the pipeline)."""
        if self.keys is not None:
            if self._pos >= len(self._order):
                return None
            raw = self.rec.read_idx(self._order[self._pos])
            self._pos += 1
            return raw
        if self._mem is not None:
            if self._pos >= len(self._order):
                return None
            raw = self._mem[self._order[self._pos]]
            self._pos += 1
            return raw
        if self.num_parts > 1:
            # sequential .rec without an index: stride-skip other hosts'
            # records — skipped bytes are read but never hit the decode
            # pool, so each host only pays decode for its own 1/num_parts
            while True:
                raw = self.rec.read()
                if raw is None:
                    return None
                i = self._seq_read
                self._seq_read += 1
                if i % self.num_parts == self.part_index:
                    return raw
        return self.rec.read()

    def _decode_into(self, raw, buf, i):
        """Worker stage: JPEG decode + augment straight into row ``i`` of
        the staging slot (GIL released in cv2/PIL; the row write is the
        worker's own memcpy, off the assembly thread)."""
        from . import recordio
        header, img = recordio.unpack_img(raw, iscolor=1)
        buf[0][i] = self._augment(img)
        buf[1][i] = float(np.asarray(header.label).ravel()[0])

    # --- producer/prefetch machinery (dmlc::ThreadedIter analog) ---------
    def _start_producer(self):
        import concurrent.futures
        import weakref
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                self._nthreads, thread_name_prefix="imgrec-decode")
        if self._bufs is None:
            # reusable staging ring: one HWC+label slot per in-flight
            # batch (a single workspace carve can't back several batches
            # decoding concurrently), allocated once and recycled
            c, h, w = self.data_shape
            self._bufs = queue.Queue()
            for _ in range(self._prefetch + 1):
                self._bufs.put(
                    (np.empty((self.batch_size, h, w, c), np.float32),
                     np.empty((self.batch_size,), np.float32)))
        self._reader_done = False
        self._queue = queue.Queue(self._prefetch)
        self._stop.clear()
        if _telemetry.enabled:
            _IO_DEPTH.labels(iter="ImageRecordIter").set(self._prefetch)
            _IO_WORKERS.labels(iter="ImageRecordIter").set(self._nthreads)
        # the thread holds only a WEAK reference between batches, so an
        # abandoned iterator stays collectable and its loop exits instead
        # of leaking the thread + pool
        self._producer_thread = threading.Thread(
            target=_imgrec_produce_loop,
            args=(weakref.ref(self), self._stop, self._queue), daemon=True)
        self._producer_thread.start()

    def _stop_producer(self):
        if getattr(self, "_producer_thread", None) is None:
            return
        self._stop.set()
        try:
            cur = threading.current_thread()
        except Exception:   # interpreter teardown: module globals cleared
            self._producer_thread = None
            return
        if self._producer_thread is cur:
            # GC collected the abandoned iterator ON the producer thread
            # (it holds the last transient strong ref) — can't self-join
            self._producer_thread = None
            return
        while self._producer_thread.is_alive():
            try:  # unblock a producer stuck on a full queue
                self._queue.get_nowait()
            except queue.Empty:
                pass
            self._producer_thread.join(timeout=0.05)
        self._producer_thread = None
        # settle in-flight decodes before the ring is recycled: a worker
        # still writing into a slot would corrupt the next epoch's batches
        if self._inflight:
            for futs, _n, buf in self._inflight:
                for f in futs:
                    if not f.cancel():
                        try:
                            f.result()
                        except Exception:  # noqa: BLE001 — epoch abandoned
                            pass
                if self._bufs is not None:
                    self._bufs.put(buf)
            self._inflight.clear()

    def _pump(self):
        """One producer turn (pipelined): keep up to ``prefetch_buffer``
        batches decoding in the pool, then finish + assemble the OLDEST
        one — batch order is exactly the reader order even though several
        batches' decodes overlap.  Returns (items_to_enqueue, done)."""
        while (not self._reader_done
               and len(self._inflight) < self._prefetch):
            raws = []
            while len(raws) < self.batch_size:
                raw = self._read_raw()
                if raw is None:
                    self._reader_done = True
                    break
                raws.append(raw)
            if not raws:
                break
            try:
                buf = self._bufs.get_nowait()
            except queue.Empty:  # can't happen by sizing; stay deadlock-free
                c, h, w = self.data_shape
                buf = (np.empty((self.batch_size, h, w, c), np.float32),
                       np.empty((self.batch_size,), np.float32))
            futs = [self._pool.submit(self._decode_into, r, buf, i)
                    for i, r in enumerate(raws)]
            self._inflight.append((futs, len(raws), buf))
        if not self._inflight:
            return [None], True
        futs, n, buf = self._inflight.popleft()
        for f in futs:
            f.result()
        batch = self._assemble_batch(buf, n)
        self._bufs.put(buf)
        pad = self.batch_size - n
        done = bool(pad) or (self._reader_done and not self._inflight)
        return ([batch, None], True) if done else ([batch], False)

    def _assemble_batch(self, buf, n):
        """Pad + transpose one decoded staging slot into a DataBatch."""
        data, label = buf
        c, h, w = self.data_shape
        # CHW output still comes from the pooled temp space (one rotating
        # slot; only the producer thread touches it).  Reuse of both the
        # carve and the ring slot is safe because nd.array's astype copy
        # (guaranteed, never aliasing) materializes the batch first.
        n_img = self.batch_size * h * w * c
        ws = self._workspace.get_space((n_img,), np.float32)
        if _telemetry.enabled:
            _IO_WS.labels(iter="ImageRecordIter").set(
                ws.nbytes + (self._prefetch + 1)
                * (data.nbytes + label.nbytes))
        chw = ws[:n_img].reshape((self.batch_size, c, h, w))
        pad = self.batch_size - n
        if pad:
            data[n:] = data[:1]
            label[n:] = label[:1]
        # one vectorized HWC->CHW for the whole batch (cheaper than 128
        # per-image strided copies, and outside the decode workers),
        # written into the pooled CHW carve instead of a fresh allocation
        np.copyto(chw, data.transpose(0, 3, 1, 2))
        return DataBatch([nd.array(chw)], [nd.array(label)], pad=pad)

    def _augment(self, img):
        c, h, w = self.data_shape
        if self.resize > 0:
            img = _resize_short(img, self.resize)
        ih, iw = img.shape[:2]
        if self.rand_crop and ih > h and iw > w:
            y = np.random.randint(0, ih - h + 1)
            x = np.random.randint(0, iw - w + 1)
        else:
            y, x = max(0, (ih - h) // 2), max(0, (iw - w) // 2)
        img = img[y:y + h, x:x + w]
        if img.shape[0] != h or img.shape[1] != w:
            img = _resize_exact(img, (w, h))
        if self.rand_mirror and np.random.rand() < 0.5:
            img = img[:, ::-1]
        # BGR->RGB + (x - mean)/std*scale as x*a + b.  cv2 releases the GIL
        # (numpy ufuncs don't), which is what lets preprocess_threads scale
        # (the reference's N decode threads, iter_image_recordio_2.cc:727).
        a = self.scale / self.std
        b = -self.mean * a
        try:
            import cv2
            rgb = cv2.cvtColor(np.ascontiguousarray(img),
                               cv2.COLOR_BGR2RGB)
            mul = tuple(float(x) for x in a) + (0.0,)
            add = tuple(float(x) for x in b) + (0.0,)
            out = cv2.multiply(rgb, mul, dtype=cv2.CV_32F)
            out = cv2.add(out, add)
        except ImportError:
            out = img[..., ::-1].astype(np.float32) * a + b
        return out                               # HWC; batch-transposed once

    def __next__(self):
        if self._done:
            raise StopIteration
        tel = _telemetry.enabled
        if tel:
            t0 = time.perf_counter()
            batch = self._queue.get()
            wait = time.perf_counter() - t0
            _IO_WAIT.labels(iter="ImageRecordIter").observe(wait)
            if _health.enabled:
                _health.monitor.note_phase("input", wait)
        else:
            batch = self._queue.get()
        if batch is None:
            self._done = True
            raise StopIteration
        if isinstance(batch, Exception):
            self._done = True
            raise batch
        if tel:
            _IO_BATCHES.labels(iter="ImageRecordIter").inc()
        return batch

    next = __next__


def _imgrec_produce_loop(ref, stop, q):
    """ImageRecordIter producer body (module-level: must not pin the
    iterator alive — see _start_producer).  Any reader/decoder exception is
    forwarded to the consumer via the queue instead of dying silently."""
    while not stop.is_set():
        it = ref()
        if it is None:
            return
        try:
            items, done = it._pump()
        except Exception as e:               # noqa: BLE001 — surfaced below
            items, done = [e, None], True
        del it
        for item in items:
            placed = False
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    placed = True
                    break
                except queue.Full:
                    if ref() is None:        # consumer abandoned us
                        return
            if not placed:
                return
        if done:
            return


def _resize_short(img, size):
    h, w = img.shape[:2]
    if h < w:
        nh, nw = size, int(w * size / h)
    else:
        nh, nw = int(h * size / w), size
    return _resize_exact(img, (nw, nh))


def _resize_exact(img, wh):
    try:
        import cv2
        return cv2.resize(img, wh)
    except ImportError:
        from PIL import Image
        mode = "RGB" if img.ndim == 3 else "L"
        return np.asarray(Image.fromarray(img, mode).resize(wh))


class LibSVMIter(DataIter):
    """libsvm sparse text format (ref src/io/iter_libsvm.cc:200); yields
    dense batches (device compute is dense on TPU — SURVEY.md §7.3 sparse)."""

    def __init__(self, data_libsvm, data_shape, batch_size, label_shape=(1,),
                 **kwargs):
        super().__init__(batch_size)
        dim = int(np.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = np.zeros(dim, np.float32)
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        data = np.stack(rows).reshape((-1,) + tuple(data_shape))
        self._inner = NDArrayIter(data, np.asarray(labels, np.float32),
                                  batch_size, label_name="label")

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def reset(self):
        self._inner.reset()

    def __next__(self):
        return next(self._inner)

    next = __next__


def ImageDetRecordIter(path_imgrec, data_shape, batch_size,
                       label_pad_width=-1, label_pad_value=-1.0,
                       path_imgidx=None, shuffle=False, mean_r=0.0,
                       mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                       std_b=1.0, part_index=0, num_parts=1, **kwargs):
    """Detection record iterator (ref src/io/iter_image_det_recordio.cc:582).

    Deviation from the reference C++ iterator: labels are emitted directly
    in the padded ``(batch, max_objects, obj_width)`` format (padded with
    ``label_pad_value``) rather than the flat header-prefixed rows the
    reference emits and every consumer immediately reshapes
    (example/ssd/dataset/iterator.py:101-124).  ``label_pad_width`` counts
    objects (rows) here; -1 estimates the maximum over the dataset.

    Augmentation kwargs are forwarded to ``CreateDetAugmenter``
    (rand_crop/rand_pad/rand_mirror/brightness/...).
    """
    from .image_detection import ImageDetIter

    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
    std = None
    if (std_r, std_g, std_b) != (1.0, 1.0, 1.0):
        std = np.array([std_r, std_g, std_b], np.float32)
    it = ImageDetIter(batch_size=batch_size, data_shape=tuple(data_shape),
                      path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                      shuffle=shuffle, part_index=part_index,
                      num_parts=num_parts, mean=mean, std=std, **kwargs)
    if label_pad_width > 0:
        if label_pad_width < it.label_shape[0]:
            raise MXNetError(
                "label_pad_width %d smaller than max object count %d"
                % (label_pad_width, it.label_shape[0]))
        it.reshape(label_shape=(label_pad_width, it.label_shape[1]))
    it.label_pad_value = label_pad_value
    return it
