"""mxnet_tpu: a TPU-native deep learning framework with the capability
surface of Apache MXNet ≈1.2 (reference: yangyu12/incubator-mxnet).

Not a port: the compute path is JAX/XLA (MXU matmuls/convs, XLA fusion, ICI
collectives via pjit/shard_map), with Pallas kernels for hot non-standard ops;
the host runtime (dependency engine, data pipeline, KVStore façade) keeps the
reference's contracts.  See SURVEY.md for the blueprint.
"""
__version__ = "0.1.0"

# the lock-order sanitizer (MXNET_LOCKCHECK=1) must patch threading
# BEFORE any submodule import so module-level locks are instrumented;
# locksmith is stdlib-only for exactly this reason
from . import locksmith as _locksmith
_locksmith.install()

# memory-pool env knobs must translate to XLA client settings BEFORE the
# first backend init (storage manager N2; no-op if jax already started)
from .storage import apply_pool_env as _apply_pool_env
_apply_pool_env()

from .base import MXNetError
from . import telemetry
from . import tracing
from . import runlog  # env-gated ledger activation (MXNET_RUNLOG_DIR/_PATH)
from .context import (Context, cpu, gpu, tpu, cpu_pinned, current_context,
                      num_gpus)
from . import engine
from . import operator  # registers the Custom op before namespace gen
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd

from .ndarray import NDArray

from . import name
from . import attribute
from .name import NameManager
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .executor import Executor

from . import initializer
from . import initializer as init
from . import optimizer
from . import amp
from . import metric
from . import lr_scheduler
from . import callback
from . import io
from . import train_loop
from .train_loop import OverlappedLoop
from . import recordio
from . import rnn
from . import kvstore as kv
from .kvstore import KVStore
from . import parallel
from . import module
from . import module as mod
from . import model
from .model import FeedForward
from . import models
from . import contrib
from . import profiler
from . import monitor as _monitor_mod
from .monitor import Monitor
from . import visualization
from . import visualization as viz
from . import rtc
from . import image
from . import image as img
from . import test_utils
from . import storage
from . import checkpoint
from . import fused
from .fused import FusedTrainer
from . import predictor
from .predictor import Predictor
from . import serving


def kvstore_create(name="local"):
    from .kvstore import create as _c
    return _c(name)
