"""Device-memory observability: owner-tagged HBM ledger + OOM forensics.

``storage.py`` exposes the raw primitives (allocator stats, live-array
census) but nothing wires them into telemetry, health verdicts or flight
dumps — an OOM today is a bare ``RESOURCE_EXHAUSTED`` with zero context.
This module is the memory analog of the health monitor (PR 7), built
from three pieces:

**Owner-tagged ledger** — the allocation choke points (Module param
init, fused-step donation pools, optimizer state creation, serving
warmup/hot-swap, io prefetch staging, checkpoint host snapshots) call
:func:`tag` with the owner that allocated the buffers.  The registry
keeps ``id(array) -> (owner, detail, weakref)``; a periodic
:func:`census` classifies ``jax.live_arrays()`` against it into
params / opt_state / activations / serving / io / checkpoint /
untagged and exports ``memwatch_owner_bytes{owner}`` plus per-device
``device_bytes_in_use`` / ``device_peak_bytes_in_use`` /
``device_bytes_limit`` gauges.  The PR 11 time-series sampler persists
those gauges into its rings for free — the census runs on its OWN
thread (``MXNET_MEMWATCH_INTERVAL``) because the sampler contractually
makes zero jax calls.

**OOM pre-flight** — ``health.register_program`` hands every new
program's cost record to :func:`preflight`: projected footprint
(args + output, + temp when ``MXNET_HEALTH_DEEP=1``) on top of the live
tagged bytes versus the allocator ``bytes_limit``.  Crossing
``MXNET_MEMWATCH_PREFLIGHT_FRACTION`` of the limit trips a health
verdict ``cause=oom_risk``, an ``oom_risk`` ledger event and a
rate-limited warning — before XLA hits the wall.

**Leak sentinel + OOM forensics** — untagged arrays surviving
``MXNET_MEMWATCH_LEAK_GENERATIONS`` censuses are flagged once into
``memory_leak_suspects_total`` with a top-offenders table (shape /
dtype / device / likely owner by shape-match against the ledger).  The
executor and serving dispatch boundaries catch ``RESOURCE_EXHAUSTED``
and call :func:`on_oom`, which dumps the flight recorder
(``reason=oom``) — the dump embeds :func:`forensics`: the per-owner
ledger, the suspects table, per-device stats and the last registered
program's footprint, next to the recorder's own memory time-series
window.

Everything is gated on the module attribute :data:`enabled` (default
OFF; ``MXNET_MEMWATCH=1`` or :func:`enable`, which implies telemetry),
so the disabled path at every hook site is a single attribute check.
Surfaces: ``/memz`` (telemetry HTTP), flight dumps, and the
``tools/memwatch.py`` CLI (snapshot / ``--watch`` / ``--diff`` /
``--smoke``).
"""
from __future__ import annotations

import logging
import threading
import time
import weakref

from . import telemetry as _telemetry
from .base import get_env

__all__ = ["enabled", "enable", "disable", "reset", "tag", "untag",
           "census", "snapshot", "forensics", "preflight", "owner_bytes",
           "is_oom", "on_oom", "start", "stop", "running", "OWNERS"]

logger = logging.getLogger(__name__)

#: single-attribute gate read by every hook site; default off.
enabled: bool = False

#: owner taxonomy of the ledger; census buckets every live array into
#: one of these (or ``untagged``).
OWNERS = ("params", "opt_state", "activations", "serving", "io",
          "checkpoint", "untagged")

#: offenders kept in the suspects table per census.
TOP_OFFENDERS = 10

# -- metrics ----------------------------------------------------------------

_OWNER_BYTES = _telemetry.gauge(
    "memwatch_owner_bytes",
    "live device bytes attributed to an owner by the memory census",
    ("owner",))
_OWNER_ARRAYS = _telemetry.gauge(
    "memwatch_owner_arrays",
    "live array count attributed to an owner by the memory census",
    ("owner",))
_DEV_IN_USE = _telemetry.gauge(
    "device_bytes_in_use",
    "allocator bytes_in_use per device (census live bytes when the "
    "backend exposes no allocator stats)",
    ("device",))
_DEV_PEAK = _telemetry.gauge(
    "device_peak_bytes_in_use",
    "allocator peak_bytes_in_use per device (census high-water mark on "
    "backends without allocator stats)",
    ("device",))
_DEV_LIMIT = _telemetry.gauge(
    "device_bytes_limit",
    "allocator bytes_limit per device (0 when the backend exposes none)",
    ("device",))
_LEAK_SUSPECTS = _telemetry.counter(
    "memory_leak_suspects_total",
    "untagged arrays that survived the leak-sentinel generation window")
_OOM_EVENTS = _telemetry.counter(
    "memwatch_oom_total",
    "RESOURCE_EXHAUSTED errors caught at a dispatch boundary",
    ("site",))
_PREFLIGHT_RISKS = _telemetry.counter(
    "memwatch_preflight_risks_total",
    "program registrations whose projected footprint crossed the "
    "pre-flight fraction of bytes_limit",
    ("program",))
_CENSUS_SECONDS = _telemetry.histogram(
    "memwatch_census_seconds",
    "wall time of one memory census pass")

# -- tag registry -----------------------------------------------------------

# id(array) -> (owner, detail, weakref-or-None).  The weakref both keeps
# the entry prunable and guards against id reuse: an entry whose referent
# died is dropped at the next census, so a recycled id can never inherit
# a stale owner.
_tags = {}
_lock = threading.Lock()

# leak sentinel state: census generation counter, id -> first-seen
# generation for untagged arrays, ids already counted as suspects.
_generation = 0
_first_seen = {}
_flagged = set()

# last census snapshot (owner totals, device stats, suspects) served by
# snapshot()/forensics() without re-walking live arrays.
_last_census = None

# census high-water mark per device — the peak fallback for backends
# (CPU) whose allocator exposes no stats.
_census_peak = {}

# last program name handed to preflight, for forensics attribution.
_last_program = None
_last_warn = {}


def _unwrap(leaf):
    """NDArray -> backing jax array; pass jax arrays through; None for
    host-side leaves (numpy, scalars) the ledger cannot track."""
    data = getattr(leaf, "_data", leaf)
    if hasattr(data, "devices") and hasattr(data, "nbytes"):
        return data
    return None


def tag(owner, leaves, detail=None):
    """Attribute the device arrays in ``leaves`` (any pytree; NDArrays
    are unwrapped) to ``owner``.  Re-tagging an id overwrites — buffers
    that change hands (donation pools) follow their latest owner.
    Returns the number of arrays tagged; 0 when disabled."""
    if not enabled:
        return 0
    try:
        import jax
        entries = []
        for leaf in jax.tree_util.tree_leaves(leaves):
            arr = _unwrap(leaf)
            if arr is None:
                continue
            try:
                ref = weakref.ref(arr)
            except TypeError:
                ref = None
            entries.append((id(arr), (owner, detail, ref)))
    except Exception:
        return 0
    if not entries:
        return 0
    with _lock:
        for key, val in entries:
            _tags[key] = val
            _first_seen.pop(key, None)
            _flagged.discard(key)
    return len(entries)


def untag(leaves):
    """Drop the ledger entries for ``leaves`` (used when an owner
    releases buffers it knows are dead, e.g. serving hot-swap)."""
    if not enabled:
        return
    try:
        import jax
        keys = [id(a) for a in
                (_unwrap(leaf) for leaf in jax.tree_util.tree_leaves(leaves))
                if a is not None]
    except Exception:
        return
    with _lock:
        for key in keys:
            _tags.pop(key, None)


def owner_bytes(owner, detail=None):
    """Live bytes of one owner straight from the ledger weakrefs — no
    ``jax.live_arrays()`` walk, cheap enough for per-request serving
    stats.  ``detail`` narrows to one tag detail (e.g. a model name)."""
    total = 0
    with _lock:
        entries = list(_tags.values())
    for own, det, ref in entries:
        if own != owner or (detail is not None and det != detail):
            continue
        arr = ref() if ref is not None else None
        if arr is None:
            continue
        try:
            if not arr.is_deleted():
                total += arr.nbytes
        except Exception:
            continue
    return total


# -- census -----------------------------------------------------------------

def _device_stats():
    """Per-device allocator stats with census fallback for backends that
    expose none; updates the device gauges."""
    import jax
    from . import storage as _storage
    out = {}
    for d in jax.local_devices():
        key = str(d)
        st = _storage.memory_stats(d)
        if st:
            in_use = int(st.get("bytes_in_use", 0))
            peak = int(st.get("peak_bytes_in_use", 0))
            limit = int(st.get("bytes_limit", 0))
            source = "allocator"
        else:
            _, in_use = _storage.live_arrays(d)
            peak = max(_census_peak.get(key, 0), in_use)
            limit = 0
            source = "census"
        _census_peak[key] = max(_census_peak.get(key, 0), in_use)
        peak = max(peak, _census_peak[key])
        out[key] = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
                    "bytes_limit": limit, "source": source}
        _DEV_IN_USE.labels(device=key).set(in_use)
        _DEV_PEAK.labels(device=key).set(peak)
        _DEV_LIMIT.labels(device=key).set(limit)
    return out


def _likely_owner(shape, dtype, tagged_live):
    """Shape/dtype match against the tagged live set — the leak table's
    best guess at who allocated an untagged buffer."""
    for (sh, dt), owner in tagged_live.items():
        if sh == shape and dt == dtype:
            return owner
    for (sh, dt), owner in tagged_live.items():
        if sh == shape:
            return owner
    return None


def census():
    """One ledger pass: classify ``jax.live_arrays()`` by owner, update
    the gauges, age the leak sentinel, refresh device stats.  Returns
    the snapshot dict (also cached for :func:`snapshot`).  Called by
    the census thread, ``/memz``, and directly by tests/tools."""
    global _generation, _last_census
    t0 = time.perf_counter()
    import jax
    from . import storage as _storage
    owners = {o: {"bytes": 0, "arrays": 0} for o in OWNERS}
    details = {}
    tagged_live = {}
    live_ids = set()
    live_skeys = set()
    suspects = []
    with _lock:
        _generation += 1
        gen = _generation
        tags = dict(_tags)
    arrs = []
    for a in jax.live_arrays():
        try:
            arrs.append((a, _storage.array_buffers(a), int(a.nbytes),
                         tuple(a.shape), str(a.dtype)))
        except Exception:       # deleted/donated buffer
            continue
    # dedupe aliasing buffers (jax caches per-shard ArrayImpl views of
    # sharded arrays, which alias the parent's storage): visit tagged
    # arrays and multi-buffer parents first so the owner attribution
    # wins and the alias contributes zero fresh bytes
    arrs.sort(key=lambda t: (id(t[0]) in tags, len(t[1])), reverse=True)
    seen_bufs = set()
    for a, bufs, nbytes, shape, dtype in arrs:
        fresh = 0
        aliased = False
        for d, ptr, nb in bufs:
            if ptr is not None:
                bkey = (id(d), ptr)
                if bkey in seen_bufs:
                    aliased = True
                    continue
                seen_bufs.add(bkey)
            fresh += nb
        if aliased and fresh == 0:
            continue            # pure alias of an already-counted array
        if bufs:
            nbytes = fresh
        key = id(a)
        live_ids.add(key)
        # sentinel identity: the first buffer pointer when available —
        # stable across aliasing views (jax may yield a cached shard
        # view instead of the original array on later walks), unlike
        # id(a)
        skey = key
        for d, ptr, _nb in bufs:
            if ptr is not None:
                skey = "%x:%x" % (id(d), ptr)   # JSON-stable
                break
        live_skeys.add(skey)
        entry = tags.get(key)
        if entry is not None:
            owner, det, ref = entry
            referent = ref() if ref is not None else None
            if ref is not None and referent is not a:
                entry = None    # id reused by a new array: not this tag
        if entry is not None:
            owner, det, _ = entry
            if owner not in owners:
                owner = "untagged"
            owners[owner]["bytes"] += nbytes
            owners[owner]["arrays"] += 1
            if det is not None:
                d = details.setdefault(owner, {})
                d[det] = d.get(det, 0) + nbytes
            tagged_live.setdefault((shape, dtype), owner)
            # a buffer that was a suspect but then got tagged is owned
            # after all — drop the sentinel state
            _first_seen.pop(skey, None)
            _flagged.discard(skey)
        else:
            owners["untagged"]["bytes"] += nbytes
            owners["untagged"]["arrays"] += 1
            if nbytes < get_env("MXNET_MEMWATCH_LEAK_MIN_BYTES", 4096,
                                int):
                # scalars and other crumbs (RNG keys, loss values) churn
                # forever below the sentinel's radar — a leak that
                # matters is big
                continue
            first = _first_seen.setdefault(skey, gen)
            age = gen - first
            suspects.append({"id": skey, "nbytes": nbytes, "shape": shape,
                             "dtype": dtype,
                             "device": str(next(iter(a.devices()))),
                             "age": age})
    # prune registry entries whose referent died and sentinel state for
    # buffers no longer live (frees the identity for safe reuse)
    with _lock:
        for key, (_, _, ref) in list(_tags.items()):
            if key not in live_ids and ref is not None and ref() is None:
                _tags.pop(key, None)
        for skey in list(_first_seen):
            if skey not in live_skeys:
                _first_seen.pop(skey, None)
                _flagged.discard(skey)

    k = get_env("MXNET_MEMWATCH_LEAK_GENERATIONS", 3, int)
    newly_flagged = []
    for s in suspects:
        s["likely_owner"] = _likely_owner(s["shape"], s["dtype"],
                                          tagged_live)
        if s["age"] >= k and s["id"] not in _flagged:
            with _lock:
                _flagged.add(s["id"])
            newly_flagged.append(s)
            _LEAK_SUSPECTS.inc()
    suspects.sort(key=lambda s: s["nbytes"], reverse=True)
    suspects = [dict(s, shape=list(s["shape"])) for s in
                suspects[:TOP_OFFENDERS] if s["age"] >= k]
    if newly_flagged:
        top = max(newly_flagged, key=lambda s: s["nbytes"])
        try:
            from . import runlog as _runlog
            if _runlog.enabled():
                _runlog.event("memory_leak_suspect",
                              new_suspects=len(newly_flagged),
                              top_nbytes=top["nbytes"],
                              top_shape=list(top["shape"]),
                              top_dtype=top["dtype"],
                              top_device=top["device"],
                              likely_owner=top.get("likely_owner"),
                              generation=gen)
        except Exception:
            pass

    for o, rec in owners.items():
        _OWNER_BYTES.labels(owner=o).set(rec["bytes"])
        _OWNER_ARRAYS.labels(owner=o).set(rec["arrays"])
    devices = _device_stats()
    total = sum(rec["bytes"] for rec in owners.values())
    tagged = total - owners["untagged"]["bytes"]
    snap = {"unix_time": time.time(), "generation": gen,
            "owners": owners, "details": details, "devices": devices,
            "suspects": suspects,
            "total_bytes": total, "tagged_bytes": tagged,
            "untagged_bytes": owners["untagged"]["bytes"],
            "coverage_pct": (100.0 * tagged / total) if total else 100.0}
    with _lock:
        _last_census = snap
    _CENSUS_SECONDS.observe(time.perf_counter() - t0)
    return snap


def snapshot(refresh=False):
    """Last census snapshot (or a fresh one when ``refresh`` / none yet);
    the ``/memz`` payload."""
    with _lock:
        snap = _last_census
    if snap is None or refresh:
        snap = census()
    return dict(snap, enabled=enabled, running=running(),
                last_program=_last_program)


# -- OOM pre-flight ---------------------------------------------------------

def preflight(pc):
    """Project a newly registered program's footprint against the
    allocator limit; called by ``health.register_program`` with the
    :class:`health.ProgramCost`.  Risk = live tagged bytes + args + out
    (+ temp when known) crossing ``MXNET_MEMWATCH_PREFLIGHT_FRACTION``
    of ``bytes_limit``.  Returns the verdict dict or None (disabled /
    no limit known)."""
    global _last_program
    if not enabled or pc is None:
        return None
    _last_program = pc.name
    from . import storage as _storage
    limit = _storage.bytes_limit()
    if limit <= 0:
        return None
    need = int(pc.arg_bytes or 0) + int(pc.out_bytes or 0) + \
        int(pc.temp_bytes or 0)
    with _lock:
        snap = _last_census
    live = snap["tagged_bytes"] if snap else 0
    frac = get_env("MXNET_MEMWATCH_PREFLIGHT_FRACTION", 0.95, float)
    projected = live + need
    verdict = {"program": pc.name, "need_bytes": need,
               "live_tagged_bytes": live, "bytes_limit": limit,
               "projected_bytes": projected,
               "risk": projected > frac * limit}
    if verdict["risk"]:
        _PREFLIGHT_RISKS.labels(program=pc.name).inc()
        try:
            from . import health as _health
            _health._VERDICT.labels(cause="oom_risk").set(1.0)
            _health._ANOMALIES.labels(cause="oom_risk").inc()
        except Exception:
            pass
        try:
            from . import runlog as _runlog
            if _runlog.enabled():
                _runlog.event("oom_risk", **verdict)
        except Exception:
            pass
        interval = get_env("MXNET_MEMWATCH_WARN_INTERVAL", 60.0, float)
        now = time.monotonic()
        if now - _last_warn.get(pc.name, -interval) >= interval:
            _last_warn[pc.name] = now
            logger.warning(
                "memwatch: program %r projects %d bytes "
                "(%d live tagged + %d args/out/temp) against a %d-byte "
                "limit — OOM risk", pc.name, projected, live, need, limit)
    return verdict


# -- OOM forensics ----------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "Out of memory", "out of memory", "OOM")


def is_oom(exc):
    """Best-effort RESOURCE_EXHAUSTED classifier (XlaRuntimeError carries
    the grpc status name in its message)."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def on_oom(exc, site="executor", program=None):
    """Forensics for a caught RESOURCE_EXHAUSTED: fresh census, ``oom``
    ledger event, flight dump (``reason=oom`` — the dump embeds
    :func:`forensics`).  Never raises; callers re-raise the original
    error.  Nested catch sites (serving wraps the executor dispatch) see
    the same exception object once — a marker attribute on the exception
    dedups (builtin exceptions don't support weakrefs)."""
    if not enabled:
        return None
    if getattr(exc, "_memwatch_handled", False):
        return None
    try:
        exc._memwatch_handled = True
    except Exception:
        pass
    _OOM_EVENTS.labels(site=site).inc()
    try:
        snap = census()
    except Exception:
        snap = None
    dump_path = None
    try:
        from . import tracing as _tracing
        dump_path = _tracing.flight.dump(reason="oom")
    except Exception:
        pass
    try:
        from . import runlog as _runlog
        if _runlog.enabled():
            owners = {o: rec["bytes"]
                      for o, rec in (snap or {}).get("owners", {}).items()}
            _runlog.event("oom", site=site, program=program or _last_program,
                          error=str(exc)[:400], owner_bytes=owners,
                          flight_dump=dump_path)
    except Exception:
        pass
    return dump_path


def forensics():
    """The flight-dump block: ledger snapshot + last registered
    program's footprint (``None`` entries when health never saw one)."""
    snap = snapshot()
    prog = None
    if _last_program is not None:
        try:
            from . import health as _health
            pc = _health.programs().get(_last_program)
            if pc is not None:
                prog = dict(pc.as_dict(), name=_last_program)
        except Exception:
            pass
    return {"census": snap, "last_program": prog}


# -- census thread ----------------------------------------------------------

_thread = None
_stop = threading.Event()


def _loop():
    while not _stop.is_set():
        try:
            census()
        except Exception:
            logger.debug("memwatch census failed", exc_info=True)
        _stop.wait(get_env("MXNET_MEMWATCH_INTERVAL", 5.0, float))


def start():
    """Start the census thread (idempotent)."""
    global _thread
    if _thread is not None and _thread.is_alive():
        return
    _stop.clear()
    _thread = threading.Thread(target=_loop, name="memwatch-census",
                               daemon=True)
    _thread.start()


def stop():
    """Stop the census thread (the ledger and gauges stay)."""
    global _thread
    _stop.set()
    t = _thread
    if t is not None:
        t.join(timeout=5.0)
    _thread = None


def running():
    return _thread is not None and _thread.is_alive()


# -- gates ------------------------------------------------------------------

def enable(census_thread=True):
    """Turn the ledger hooks on (implies telemetry — the gauges feed the
    time-series sampler).  ``census_thread=False`` for tests that drive
    :func:`census` manually."""
    global enabled
    _telemetry.enable()
    enabled = True
    if census_thread:
        start()


def disable():
    global enabled
    enabled = False
    stop()


def reset():
    """Test isolation: drop the ledger, sentinel state and cached census."""
    global _generation, _last_census, _last_program
    stop()
    with _lock:
        _tags.clear()
        _first_seen.clear()
        _flagged.clear()
        _generation = 0
        _last_census = None
    _census_peak.clear()
    _last_warn.clear()
    _last_program = None


if get_env("MXNET_MEMWATCH", False, bool):
    enable()
