"""FusedTrainer: whole-train-step compilation (forward + backward +
optimizer in ONE XLA program).

TPU-native answer to the reference's dispatch-overhead amortizers
(SURVEY.md §7.3 "eager per-op dispatch cost": CachedOp + engine bulking,
``MXNET_EXEC_BULK_EXEC_*`` of graph_executor.cc:1463-1483).  Where the
reference bulks engine segments, the TPU design compiles the ENTIRE
training step — model forward, loss, gradients, and the optimizer update
over every parameter — into a single donated-buffer XLA executable: zero
per-op and per-parameter dispatch, buffers reused in place.

    net = vision.resnet50_v1(); net.initialize(); net.hybridize()
    ft = FusedTrainer(net, "softmax_cross_entropy", "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    for x, y in batches:
        loss = ft.step(x, y)
    ft.sync_params()           # write trained values back into the Block

Supported optimizers: sgd (momentum/wd/nesterov-free form).  Learning rate
is a traced scalar, so schedules don't retrace.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from . import program_cache as _program_cache

__all__ = ["FusedTrainer"]


def _softmax_ce(logits, labels):
    from .ops.nn import streaming_ce
    return jnp.mean(streaming_ce(logits.reshape(-1, logits.shape[-1]),
                                 labels.reshape(-1)))


_LOSSES: Dict[str, Callable] = {"softmax_cross_entropy": _softmax_ce}


class FusedTrainer:
    """One-executable training step over a hybridizable Gluon block."""

    def __init__(self, net, loss: Union[str, Callable] = "softmax_cross_entropy",
                 optimizer: str = "sgd", optimizer_params: Optional[dict] = None,
                 dtype: str = "float32"):
        from . import symbol as sym_mod
        from .executor import _Plan

        if dtype not in ("float32", "bfloat16", "float16"):
            raise MXNetError("FusedTrainer dtype must be float32/bfloat16/"
                             "float16, got %r" % dtype)
        # mixed precision (reference analog: optimizer.py multi_precision
        # SGD fp16 master weights): master params/momenta stay f32, the
        # forward/backward computes in `dtype`; the cast sits inside the
        # differentiated function so grads arrive f32 automatically
        self._compute_dtype = None if dtype == "float32" \
            else jnp.dtype(dtype)

        p = dict(optimizer_params or {})
        self._lr = float(p.pop("learning_rate", 0.01))
        self._momentum = float(p.pop("momentum", 0.0))
        self._wd = float(p.pop("wd", 0.0))
        if optimizer != "sgd" or p:
            raise MXNetError(
                "FusedTrainer supports optimizer='sgd' with learning_rate/"
                "momentum/wd; use gluon.Trainer for other optimizers "
                "(got %r with extras %s)" % (optimizer, sorted(p)))
        if isinstance(loss, str):
            if loss not in _LOSSES:
                raise MXNetError("unknown loss %r (built-ins: %s; or pass "
                                 "a callable(logits, labels) -> scalar, or "
                                 "a gluon.loss.Loss block)"
                                 % (loss, sorted(_LOSSES)))
            loss = _LOSSES[loss]
        else:
            from .gluon.loss import Loss as _GluonLoss
            if isinstance(loss, _GluonLoss):
                # public gluon loss traced straight into the fused step:
                # per-example losses are averaged to the scalar the
                # gradient needs (gluon.Trainer's mean-loss convention)
                blk = loss

                def loss(logits, labels, _blk=blk):
                    from .ndarray.ndarray import NDArray
                    out = _blk(NDArray(logits), NDArray(labels))
                    return jnp.mean(out._data.astype(jnp.float32))
        self._loss = loss

        self._net = net
        out_sym = net(sym_mod.var("data"))
        self._plan = _Plan(out_sym, train=True)
        params = net.collect_params()
        self._arg_names = [n for n in self._plan.arg_names if n != "data"]
        # private COPIES: step() donates these buffers to XLA, and donating
        # the arrays still referenced by the Block's Parameters would leave
        # the net holding deleted buffers
        args = {}
        for n in self._arg_names:
            try:
                args[n] = jnp.array(params[n].data()._data, copy=True)
            except Exception as e:
                raise MXNetError(
                    "FusedTrainer needs materialized parameters — run one "
                    "forward batch (or initialize with known shapes) "
                    "first: %s" % e) from e
        auxs = {n: jnp.array(params[n].data()._data, copy=True)
                for n in self._plan.aux_names}
        moms = ({k: jnp.zeros_like(v) for k, v in args.items()}
                if self._momentum != 0.0 else {})
        self._state = (args, auxs, moms)
        self._params = params
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            _memwatch.tag("params", (args, auxs), detail="fused_trainer")
            _memwatch.tag("opt_state", moms, detail="fused_trainer")
            # the Block's own Parameter arrays stay live alongside the
            # private donated copies — ledger them too
            blk = {}
            for n, p in params.items():
                try:
                    blk[n] = p.data()._data
                except Exception:
                    continue
            _memwatch.tag("params", blk, detail="block")
        n_rng = max(1, self._plan.n_rng)
        self._keys = jnp.zeros((n_rng, 2), jnp.uint32)

        plan = self._plan
        loss_fn = self._loss
        momentum, wd = self._momentum, self._wd
        cdt = self._compute_dtype
        # gluon.Trainer parity: weight decay applies only to weights/gammas
        # (optimizer.py wd_mult convention — biases/betas are exempt)
        wd_mult = {n: (1.0 if n.endswith(("_weight", "_gamma")) else 0.0)
                   for n in self._arg_names}

        _program_cache.ensure_enabled()

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def _step(args, auxs, moms, data, labels, lr, keys):
            def loss_of(a):
                if cdt is not None:
                    a = {k: v.astype(cdt) for k, v in a.items()}
                    d = data.astype(cdt)
                else:
                    d = data
                outs, new_aux = plan.execute({**a, "data": d}, auxs,
                                             keys)
                # keep aux (BN moving stats) dtype stable across steps:
                # donated buffers must keep their f32 layout
                new_aux = {k: v.astype(auxs[k].dtype)
                           for k, v in new_aux.items()}
                return loss_fn(outs[0], labels), new_aux

            (loss, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(args)
            new_args, new_moms = {}, {}
            # inline SGD below carries the same atlas scope the Optimizer
            # classes get, so /programz ranks it alongside fused_step paths
            with jax.named_scope("Optimizer::SGD"):
                for k in args:
                    g = grads[k].astype(args[k].dtype)
                    if wd:
                        g = g + (wd * wd_mult[k]) * args[k]
                    if momentum != 0.0:
                        m2 = momentum * moms[k] - lr * g
                        new_args[k] = args[k] + m2
                        new_moms[k] = m2
                    else:
                        new_args[k] = args[k] - lr * g
            return new_args, new_aux, new_moms, loss

        self._jstep = _step

    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        """Traced scalar — no recompilation on schedule changes."""
        self._lr = float(lr)

    def step(self, data, labels):
        """One fused train step; returns the (device-async) loss NDArray."""
        from .ndarray.ndarray import NDArray
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        l = labels._data if isinstance(labels, NDArray) \
            else jnp.asarray(labels)
        args, auxs, moms = self._state
        if self._plan.n_rng:
            # fresh threefry keys per step (CachedOp parity) — a constant
            # key would freeze every dropout mask for the whole run
            from . import random as _random
            keys = jnp.stack([_random.next_key()
                              for _ in range(self._plan.n_rng)])
        else:
            keys = self._keys
        from . import health as _health
        first_health = (_health.enabled
                        and not getattr(self, "_health_registered", False))
        donated_in = None
        if first_health:
            # lowering-only analysis: no compile, the dispatch below still
            # owns the one and only compilation of this program
            self._health_registered = True
            import os as _os
            _health.register_program(
                "fused_trainer_step", self._jstep,
                (args, auxs, moms, d, l, jnp.float32(self._lr), keys),
                donated=True,
                env={k: _os.environ.get(k)
                     for k in self._plan.env_keys})
            donated_in = (args, auxs, moms)
        args, auxs, moms, loss = self._jstep(
            args, auxs, moms, d, l, jnp.float32(self._lr), keys)
        if donated_in is not None:
            # runtime donation audit: the old state buffers must now be
            # invalidated, or the in-place chain silently broke
            _health.audit_donation("fused_trainer_step", donated_in)
        self._state = (args, auxs, moms)
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            # donation handed the old buffers to XLA — the outputs are
            # fresh arrays that must re-enter the ledger every step
            _memwatch.tag("params", (args, auxs), detail="fused_trainer")
            _memwatch.tag("opt_state", moms, detail="fused_trainer")
        if _health.enabled:
            _health.monitor.on_step("fused_trainer_step")
        ctx = data.context if isinstance(data, NDArray) else None
        return NDArray(loss, ctx)

    def sync_params(self):
        """Write the trained values back into the Block's Parameters
        (for checkpointing / switching back to eager).

        Writes COPIES: the next step() donates this trainer's state buffers
        to XLA, and handing the Parameters the originals would leave the
        Block holding deleted arrays after a mid-training sync.
        """
        args, auxs, _ = self._state
        for n in self._arg_names:
            self._params[n].data()._data = jnp.array(args[n], copy=True)
        for n in self._plan.aux_names:
            self._params[n].data()._data = jnp.array(auxs[n], copy=True)
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            _memwatch.tag("params",
                          {n: self._params[n].data()._data
                           for n in (*self._arg_names,
                                     *self._plan.aux_names)},
                          detail="block")
