"""Evaluation metrics (parity: python/mxnet/metric.py — registry +
Accuracy/TopK/F1/MCC/Perplexity/MAE/MSE/RMSE/CrossEntropy/NLL/Pearson/Loss/
CustomMetric/CompositeEvalMetric)."""
from __future__ import annotations

import math
from typing import Any, List, Optional

import numpy as np

from .base import Registry, MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "create", "np", "register"]

_registry = Registry("metric")


def register(klass):
    _registry.register(klass.__name__, klass)
    return klass


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise MXNetError("labels/preds count mismatch: %d vs %d"
                         % (len(labels), len(preds)))


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        # list names stay lists (multi-value metrics like the SSD MultiBox
        # CE+SmoothL1 pair; get_name_value zips them)
        self.name = list(name) if isinstance(name, (list, tuple)) \
            else str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names if n in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names if n in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_config(self):
        return {"metric": self.__class__.__name__, "name": self.name,
                **self._kwargs}

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def update_dict(self, label, pred):
        for m in self.metrics:
            m.update_dict(label, pred)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return names, values


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > label.ndim:
                pred = np.argmax(pred, axis=self.axis)
            pred = pred.astype(np.int32).ravel()
            label = label.astype(np.int32).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__("%s_%d" % (name, top_k), **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype(np.int32)
            topk = np.argsort(-pred, axis=1)[:, :self.top_k]
            for i in range(label.shape[0]):
                self.sum_metric += int(label[i] in topk[i])
            self.num_inst += label.shape[0]


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).ravel()
            if pred.ndim > 1:
                pred = np.argmax(pred, axis=1)
            pred = pred.ravel()
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (binary)."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).ravel()
            if pred.ndim > 1:
                pred = np.argmax(pred, axis=1)
            pred = pred.ravel()
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            denom = math.sqrt((self._tp + self._fp) * (self._tp + self._fn) *
                              (self._tn + self._fp) * (self._tn + self._fn))
            mcc = (self._tp * self._tn - self._fp * self._fn) / max(denom, 1e-12)
            self.sum_metric = mcc
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.astype(np.int32).ravel()
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss += -np.log(np.maximum(probs, 1e-10)).sum()
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += np.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += ((label - pred) ** 2).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label).ravel(), _as_np(pred)
            probs = pred[np.arange(label.shape[0]), label.astype(np.int64)]
            self.sum_metric += (-np.log(probs + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)
        self.eps = eps


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label).ravel(), _as_np(pred).ravel()
            self.sum_metric += np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in preds:
            loss = _as_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


@register
class Torch(Loss):
    def __init__(self, name="torch", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False, **kwargs):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__("custom(%s)" % name, **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                s, n = reval
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += reval
                self.num_inst += 1


def create(metric, *args, **kwargs):
    if callable(metric) and not isinstance(metric, EvalMetric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        c = CompositeEvalMetric()
        for m in metric:
            c.add(create(m))
        return c
    if metric in ("acc",):
        metric = "accuracy"
    if metric in ("ce",):
        metric = "crossentropy"
    if metric.lower() == "crossentropy":
        return CrossEntropy(*args, **kwargs)
    if metric.lower() == "nll_loss":
        return NegativeLogLikelihood(*args, **kwargs)
    if metric.lower().startswith("top_k_accuracy"):
        return TopKAccuracy(*args, **kwargs)
    return _registry.get(metric)(*args, **kwargs)
