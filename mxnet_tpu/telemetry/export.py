"""Exporters: Prometheus text exposition, JSON snapshot, /metrics HTTP.

The text format follows the Prometheus text-exposition rules
(``# HELP`` / ``# TYPE`` headers, escaped label values, ``_bucket``/
``_sum``/``_count`` series for histograms) so a stock Prometheus scrape of
the optional ``http.server`` endpoint works unmodified.  The JSON snapshot
carries the same data as one nested dict for programmatic consumers
(tests, dashboards, the bench harness).
"""
from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from typing import Dict, Optional

from ..base import get_env
from .registry import Counter, Gauge, Histogram, MetricRegistry

__all__ = ["prometheus_text", "snapshot", "snapshot_json",
           "start_http_server", "stop_http_server"]


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))


def _fmt_labels(names, values, extra: str = "") -> str:
    parts = ['%s="%s"' % (n, _escape_label(v))
             for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{%s}" % ",".join(parts) if parts else ""


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(registry: MetricRegistry) -> str:
    """The whole registry in Prometheus text-exposition format."""
    lines = []
    for fam in registry.collect():
        lines.append("# HELP %s %s" % (fam.name, _escape_help(fam.help)))
        lines.append("# TYPE %s %s" % (fam.name, fam.kind))
        for labelvalues, data in fam.samples():
            if isinstance(fam, Histogram):
                for bound, cum in data["buckets"].items():
                    lines.append("%s_bucket%s %d" % (
                        fam.name,
                        _fmt_labels(fam.labelnames, labelvalues,
                                    'le="%s"' % bound),
                        cum))
                lbl = _fmt_labels(fam.labelnames, labelvalues)
                lines.append("%s_sum%s %s"
                             % (fam.name, lbl, _fmt_value(data["sum"])))
                lines.append("%s_count%s %d"
                             % (fam.name, lbl, data["count"]))
            else:
                lines.append("%s%s %s" % (
                    fam.name, _fmt_labels(fam.labelnames, labelvalues),
                    _fmt_value(data)))
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot(registry: MetricRegistry) -> Dict[str, dict]:
    """JSON-able snapshot: name -> {type, help, samples:[{labels, ...}]}.

    Counter/gauge samples carry ``value``; histogram samples carry
    ``buckets`` (cumulative, keyed by upper bound), ``sum`` and ``count``.
    """
    out: Dict[str, dict] = {}
    for fam in registry.collect():
        samples = []
        for labelvalues, data in fam.samples():
            entry = {"labels": dict(zip(fam.labelnames, labelvalues))}
            if isinstance(fam, Histogram):
                entry.update(data)
            else:
                entry["value"] = data
            samples.append(entry)
        out[fam.name] = {"type": fam.kind, "help": fam.help,
                         "samples": samples}
    return out


def snapshot_json(registry: MetricRegistry, **json_kwargs) -> str:
    return json.dumps(snapshot(registry), **json_kwargs)


# ---------------------------------------------------------------------------
# optional stdlib HTTP endpoint (gated by MXNET_TELEMETRY_PORT)
# ---------------------------------------------------------------------------
_server = None
_server_thread = None
_server_lock = threading.Lock()


def start_http_server(port: int, registry: MetricRegistry,
                      host: str = "127.0.0.1"):
    """Serve ``/metrics`` (text exposition), ``/metrics.json``,
    ``/statusz`` (health snapshot), ``/programz`` (registered XLA
    programs with their atlas per-scope tables), ``/memz`` (owner-tagged
    memory ledger; ``?refresh=1`` forces a fresh census) and
    ``/timeseriesz`` (multi-resolution metric history;
    ``?window=SECS&prefix=NAME`` to filter, ``?format=ascii`` for
    sparklines) on a daemon thread.
    ``/programz?top_k=N`` bounds each program's scope table.  Binds loopback by
    default — the wire is unauthenticated, so exposing it wider is an
    explicit operator choice (``MXNET_TELEMETRY_HOST``).  Returns the
    bound port."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib API
            path, _, query = self.path.partition("?")
            if path in ("/", "/metrics"):
                body = prometheus_text(registry).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = snapshot_json(registry).encode()
                ctype = "application/json"
            elif path == "/statusz":
                # lazy import: health pulls in the telemetry package, so a
                # top-level import here would be circular
                from .. import health as _health
                body = json.dumps(_health.statusz()).encode()
                ctype = "application/json"
            elif path == "/timeseriesz":
                # lazy import: the package init imports this module first
                from . import timeseries as _ts
                window = None
                prefix = None
                fmt = "json"
                for part in query.split("&"):
                    if part.startswith("window="):
                        try:
                            window = float(part[len("window="):])
                        except ValueError:
                            pass
                    elif part.startswith("prefix="):
                        prefix = part[len("prefix="):]
                    elif part.startswith("format="):
                        fmt = part[len("format="):]
                snap = _ts.snapshot(window_seconds=window, prefix=prefix)
                if fmt == "ascii":
                    body = _ts.render_ascii(snap).encode()
                    ctype = "text/plain; charset=utf-8"
                else:
                    body = json.dumps(
                        {"interval": _ts.store().interval,
                         "running": _ts.running(),
                         "series": snap}).encode()
                    ctype = "application/json"
            elif path == "/memz":
                # lazy import for the same circularity reason as /statusz.
                # ?refresh=1 forces a fresh census (a jax.live_arrays walk)
                # instead of serving the census thread's last snapshot.
                from .. import memwatch as _memwatch
                refresh = any(part == "refresh=1"
                              for part in query.split("&"))
                body = json.dumps(_memwatch.snapshot(refresh=refresh),
                                  default=str).encode()
                ctype = "application/json"
            elif path == "/healthz":
                from .. import health as _health
                body = json.dumps(_health.healthz()).encode()
                ctype = "application/json"
            elif path == "/allz":
                # one round-trip for scrape consumers (the fleet
                # collector): statusz + healthz + memz + a full metrics
                # snapshot + a bounded timeseries window.  Each block is
                # independent — one failing subsystem must not take the
                # whole scrape down.
                window = get_env("MXNET_FLEET_ALLZ_WINDOW", 60.0, float)
                for part in query.split("&"):
                    if part.startswith("window="):
                        try:
                            window = float(part[len("window="):])
                        except ValueError:
                            pass
                doc = {"unix_time": time.time()}
                try:
                    from .. import health as _health
                    doc["statusz"] = _health.statusz()
                    doc["healthz"] = _health.healthz()
                except Exception:
                    pass
                try:
                    from .. import memwatch as _memwatch
                    doc["memz"] = _memwatch.snapshot(refresh=False)
                except Exception:
                    pass
                doc["metrics"] = snapshot(registry)
                try:
                    from . import timeseries as _ts
                    doc["timeseries"] = _ts.trailing(
                        window_seconds=window)
                except Exception:
                    pass
                body = json.dumps(doc, default=str).encode()
                ctype = "application/json"
            elif path == "/fleetz":
                # only meaningful on the collector process
                from . import fleet as _fleet
                if not _fleet.running():
                    self.send_error(404, "no fleet collector running")
                    return
                window = None
                for part in query.split("&"):
                    if part.startswith("window="):
                        try:
                            window = float(part[len("window="):])
                        except ValueError:
                            pass
                body = json.dumps(_fleet.fleetz(window=window),
                                  default=str).encode()
                ctype = "application/json"
            elif path == "/programz":
                # lazy imports for the same circularity reason as /statusz
                from .. import atlas as _atlas
                from .. import health as _health
                top_k = 10
                for part in query.split("&"):
                    if part.startswith("top_k="):
                        try:
                            top_k = int(part[len("top_k="):])
                        except ValueError:
                            pass
                doc = {"programs": {n: pc.as_dict()
                                    for n, pc in _health.programs().items()},
                       "atlas": _atlas.snapshot(top_k=top_k)}
                body = json.dumps(doc).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802 - stdlib API
            # /flightz: remote flight-recorder dump trigger (the fleet
            # collector fires this at the offending rank when a page-
            # severity alert fires, so the forensic snapshot is captured
            # at fire time).  The reason string is sanitized — it ends
            # up as a metric label and in the dump filename's doc.
            path, _, query = self.path.partition("?")
            if path != "/flightz":
                self.send_error(404)
                return
            reason = "fleet_alert"
            for part in query.split("&"):
                if part.startswith("reason="):
                    reason = urllib.parse.unquote(part[len("reason="):])
            reason = re.sub(r"[^A-Za-z0-9_.-]", "_", reason)[:64] \
                or "fleet_alert"
            try:
                from .. import tracing as _tracing
                dump_path = _tracing.flight.dump(reason=reason)
            except Exception:
                dump_path = None
            body = json.dumps({"path": dump_path,
                               "reason": reason}).encode()
            self.send_response(200 if dump_path else 500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # keep scrapes out of stderr
            pass

    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        srv = http.server.ThreadingHTTPServer((host, int(port)), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="mxtpu-telemetry-http", daemon=True)
        t.start()
        _server, _server_thread = srv, t
        return srv.server_address[1]


def stop_http_server():
    global _server, _server_thread
    with _server_lock:
        if _server is None:
            return
        _server.shutdown()
        _server.server_close()
        _server = None
        _server_thread = None
