"""Fleet control plane: discovery, cross-process scrape/merge, alerting.

Every process with ``MXNET_TELEMETRY_PORT`` exports rich per-process
endpoints (``/statusz``, ``/timeseriesz``, ``/memz``, ``/healthz``) —
but nothing watches a *gang* of them as one system.  This module is the
Monarch/Borgmon-style pull layer on top:

- **discovery** — :func:`register_endpoint` drops a JSON endpoint file
  (rank, role, pid, host, port, run_id) into ``MXNET_FLEET_DIR`` and
  keeps its mtime fresh from a heartbeat thread; :func:`discover` reaps
  files whose mtime is older than ``MXNET_FLEET_STALE_AFTER``, so a
  SIGKILLed rank disappears from the fleet view without coordination.
- **scrape + merge** — :class:`FleetCollector` polls every endpoint's
  consolidated ``/allz`` document once per ``MXNET_FLEET_SCRAPE_INTERVAL``
  (per-target timeout + exponential backoff, ``fleet_scrape_*`` self-
  metrics) and lands the samples in rank-labeled multi-resolution ring
  buffers (:class:`FleetStore`, reusing the timeseries tiers), plus a
  derived layer: fleet step rate, ``fleet_mfu_pct``, straggler skew
  (max/median step time), HBM by owner and by rank, per-model QPS and
  shed rate.  The merged view is served from the collector process's own
  ``/fleetz`` endpoint and embedded in its flight dumps.
- **alerting** — declarative :class:`AlertRule` s (``threshold``,
  ``delta``, ``absence``, multi-window ``burn_rate``) over any fleet or
  per-rank series.  A fire emits a ``fleet_alert`` runlog event, bumps
  ``fleet_alerts_total{rule,severity}`` and — for page severity — POSTs
  the *offending rank's* ``/flightz`` trigger so the forensic snapshot
  is captured at fire time, not at postmortem time.  Firing is edge-
  triggered and debounced (``MXNET_FLEET_ALERT_DEBOUNCE``): a persisting
  condition fires exactly once until it resolves.

Scraped-quantile convention: ``/timeseriesz`` and ``/allz`` serialize a
histogram quantile that falls in the +Inf overflow bucket as JSON
``null``.  :func:`quantile_from_buckets` keeps that convention on the
client side (``None`` = off-scale, ``0.0`` = no observations), and the
dashboard renders it ``>max`` — an off-scale tail must never read as 0.

Lock discipline (graftlint GL003): no HTTP, file or runlog I/O happens
under the store or collector locks — scrape documents are fetched and
parsed first, then appended under the lock; alert actions are collected
under the lock and executed after it is released.  All threads are
daemons stopped via ``Event`` + joined with a timeout (GL008).

The dashboard client lives in ``tools/fleetwatch.py``; the protocol and
rule table are documented in docs/observability.md ("Fleet").
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import get_env
from .. import telemetry as _telemetry
from . import timeseries as _timeseries

__all__ = ["register_endpoint", "unregister_endpoint", "endpoint_path",
           "discover", "quantile_from_buckets", "FleetStore", "AlertRule",
           "FleetCollector", "register_rule", "rules", "reset_rules",
           "default_rules", "start_collector", "stop_collector", "running",
           "collector", "fleetz", "flight_block", "reset"]

# -- self-metrics (GL005: every name below is a row in the metric table
# of docs/observability.md) -------------------------------------------------

_SCRAPES = _telemetry.counter(
    "fleet_scrape_total",
    "fleet collector scrapes completed, by target", ("target",))
_SCRAPE_ERRS = _telemetry.counter(
    "fleet_scrape_errors_total",
    "fleet scrape failures (connect/timeout/parse), by target", ("target",))
_SCRAPE_TIME = _telemetry.histogram(
    "fleet_scrape_seconds",
    "wall time of one target scrape: /allz round-trip plus merge",
    ("target",))
_TARGETS = _telemetry.gauge(
    "fleet_targets",
    "endpoint files currently live in the fleet directory")
_REAPED = _telemetry.counter(
    "fleet_reaped_endpoints_total",
    "stale endpoint files reaped from the fleet directory by mtime")
_STEP_RATE = _telemetry.gauge(
    "fleet_step_rate",
    "aggregate optimization steps/s summed across scraped ranks")
_FLEET_MFU = _telemetry.gauge(
    "fleet_mfu_pct",
    "mean live MFU percent across ranks reporting step_mfu_pct")
_SKEW = _telemetry.gauge(
    "fleet_straggler_skew",
    "max/median step-time ratio across ranks (straggler signal)")
_HBM_OWNER = _telemetry.gauge(
    "fleet_hbm_bytes",
    "fleet-wide HBM bytes by memwatch owner, summed across ranks",
    ("owner",))
_RANK_HBM = _telemetry.gauge(
    "fleet_rank_hbm_bytes",
    "per-rank device bytes in use, summed over the rank's devices",
    ("rank",))
_HBM_FRAC = _telemetry.gauge(
    "fleet_hbm_used_frac",
    "worst-rank HBM used/limit fraction across the fleet")
_SERVING_P99 = _telemetry.gauge(
    "fleet_serving_p99_seconds",
    "worst-rank serving request p99 (NaN while the tail is off-scale)")
_MODEL_QPS = _telemetry.gauge(
    "fleet_model_qps",
    "fleet-wide ok-outcome requests/s by served model", ("model",))
_MODEL_SHED = _telemetry.gauge(
    "fleet_model_shed_rate",
    "fleet-wide rejected-outcome requests/s by served model", ("model",))
_ALERTS_TOTAL = _telemetry.counter(
    "fleet_alerts_total",
    "alert-rule fires by rule and severity", ("rule", "severity"))
_ALERTS_ACTIVE = _telemetry.gauge(
    "fleet_alerts_active",
    "currently-firing alert instances by severity", ("severity",))

_SEVERITIES = ("warn", "page")

#: metric-name prefixes merged into the fleet store (bounds the ring
#: count per rank; empty string = merge everything).
_DEFAULT_PREFIXES = ("step_,worker_,serving_,device_,memwatch_,"
                     "trainer_,health_,kvstore_")


def _median(values):
    vals = sorted(values)
    n = len(vals)
    if n == 0:
        return 0.0
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


# ---------------------------------------------------------------------------
# endpoint registration + discovery
# ---------------------------------------------------------------------------

def _self_identity():
    role = os.environ.get("DMLC_ROLE", "worker") or "worker"
    key = "DMLC_WORKER_ID" if role == "worker" else "DMLC_SERVER_ID"
    try:
        rank = int(os.environ.get(key, "0") or "0")
    except ValueError:
        rank = 0
    return role, rank


def _write_endpoint(path, doc):
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


class _Heartbeat(threading.Thread):
    """Daemon loop: rewrite the endpoint file every ``interval`` seconds
    so its mtime stays fresh (and the file resurrects if a collector's
    reaper raced a long GC pause)."""

    def __init__(self, path, doc, interval):
        super().__init__(name="mxtpu-fleet-heartbeat", daemon=True)
        self._path = path
        self._doc = doc
        self._interval = float(interval)
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.wait(self._interval):
            try:
                self._doc["unix_time"] = time.time()
                _write_endpoint(self._path, self._doc)
            except Exception:
                pass  # a full disk must not take the process down

    def halt(self, timeout: float = 2.0):
        self._stop_evt.set()
        self.join(timeout)


_endpoint_lock = threading.Lock()
_endpoint_file: Optional[str] = None
_heartbeat: Optional[_Heartbeat] = None


def register_endpoint(port, fleet_dir=None, host=None, run_id=None):
    """Announce this process's telemetry endpoint in the fleet directory.

    Writes ``endpoint_<role><rank>_<pid>.json`` atomically and starts a
    heartbeat thread that keeps the mtime fresh.  Idempotent (the
    previous registration is replaced).  Returns the file path, or None
    when no fleet directory is configured."""
    if fleet_dir is None:
        fleet_dir = get_env("MXNET_FLEET_DIR", None)
    if not fleet_dir:
        return None
    if host is None:
        host = get_env("MXNET_TELEMETRY_HOST", "127.0.0.1")
    if run_id is None:
        run_id = get_env("MXNET_RUN_ID", "")
    role, rank = _self_identity()
    os.makedirs(fleet_dir, exist_ok=True)
    path = os.path.join(fleet_dir, "endpoint_%s%d_%d.json"
                        % (role, rank, os.getpid()))
    doc = {"rank": rank, "role": role, "pid": os.getpid(), "host": host,
           "port": int(port), "run_id": run_id, "unix_time": time.time()}
    _write_endpoint(path, doc)
    hb = _Heartbeat(path, dict(doc),
                    get_env("MXNET_FLEET_HEARTBEAT", 5.0, float))
    global _endpoint_file, _heartbeat
    with _endpoint_lock:
        old, _heartbeat = _heartbeat, hb
        old_file, _endpoint_file = _endpoint_file, path
    if old is not None:
        old.halt()
    if old_file and old_file != path:
        try:
            os.unlink(old_file)
        except OSError:
            pass
    hb.start()
    return path


def unregister_endpoint():
    """Stop the heartbeat and remove this process's endpoint file."""
    global _endpoint_file, _heartbeat
    with _endpoint_lock:
        hb, _heartbeat = _heartbeat, None
        path, _endpoint_file = _endpoint_file, None
    if hb is not None:
        hb.halt()
    if path:
        try:
            os.unlink(path)
        except OSError:
            pass


def endpoint_path():
    with _endpoint_lock:
        return _endpoint_file


def discover(fleet_dir=None, stale_after=None, reap=True, now=None):
    """Parse every live endpoint file; returns {target_id: endpoint doc}
    with ``target_id = "<role><rank>"``.  Files whose mtime is older
    than ``stale_after`` are reaped (unlinked + counted) when ``reap``."""
    if fleet_dir is None:
        fleet_dir = get_env("MXNET_FLEET_DIR", None)
    if stale_after is None:
        stale_after = get_env("MXNET_FLEET_STALE_AFTER", 30.0, float)
    now = time.time() if now is None else float(now)
    out: Dict[str, dict] = {}
    if not fleet_dir or not os.path.isdir(fleet_dir):
        return out
    for name in sorted(os.listdir(fleet_dir)):
        if not (name.startswith("endpoint_") and name.endswith(".json")):
            continue
        path = os.path.join(fleet_dir, name)
        try:
            age = now - os.stat(path).st_mtime
        except OSError:
            continue  # raced another reaper
        if age > stale_after:
            if reap:
                try:
                    os.unlink(path)
                    _REAPED.inc()
                except OSError:
                    pass
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            tid = "%s%d" % (doc.get("role", "worker"),
                            int(doc.get("rank", 0)))
            doc["id"] = tid
            out[tid] = doc
        except (OSError, ValueError, TypeError):
            continue  # torn write: the next heartbeat repairs it
    return out


# ---------------------------------------------------------------------------
# scraped-histogram quantiles (the JSON-null overflow convention)
# ---------------------------------------------------------------------------

def quantile_from_buckets(sample, q):
    """Client-side mirror of ``Histogram.quantile`` over a scraped
    snapshot sample (``{"buckets": {bound: cumulative}, "count": n}``).

    Returns 0.0 with no observations and ``None`` when the target falls
    in the +Inf overflow bucket — the same "off-scale is null, not a
    number" convention ``/timeseriesz`` uses, so a merged fleet series
    can never render an off-scale tail as a healthy 0."""
    try:
        n = float(sample.get("count") or 0)
    except (AttributeError, TypeError, ValueError):
        return 0.0
    if n <= 0:
        return 0.0
    bounds = []
    for bound, cum in (sample.get("buckets") or {}).items():
        try:
            b = float(bound)
        except (TypeError, ValueError):
            continue  # the "+Inf" key
        if math.isfinite(b):
            bounds.append((b, float(cum)))
    bounds.sort()
    target = q * n
    prev_cum, lo = 0.0, 0.0
    for bound, cum in bounds:
        if cum >= target:
            c = cum - prev_cum
            frac = (target - prev_cum) / c if c else 0.0
            return lo + (bound - lo) * frac
        prev_cum, lo = cum, bound
    return None  # off scale: beyond the top finite bound


# ---------------------------------------------------------------------------
# merged store: rank-labeled multi-resolution rings
# ---------------------------------------------------------------------------

class FleetStore:
    """Rank-labeled ring buffers over scraped samples, reusing the
    timeseries tier machinery (one :class:`timeseries._Series` per
    ``metric:stat{labels,rank=R}``; counters become windowed rates
    across scrape ticks, exactly like the in-process sampler)."""

    QUANTILES = (("p50", 0.5), ("p99", 0.99))

    def __init__(self, interval: float,
                 tiers: Sequence[Tuple[int, int]]
                 = _timeseries.DEFAULT_TIERS):
        self.interval = float(interval)
        self.tier_spec = tuple(tiers)
        self._lock = threading.Lock()
        self._series: Dict[str, object] = {}

    def push_rows(self, rows, now):
        """Append pre-computed rows ``(metric, stat, labels, kind, raw)``
        where raw is ``("counter", cumulative)`` for rate-derived series
        or a float/None sample.  Returns the values actually pushed as
        ``(metric, stat, labels, value)`` (rates resolved)."""
        out = []
        with self._lock:
            for metric, stat, labels, kind, raw in rows:
                key = _timeseries.series_key(metric, stat, labels)
                s = self._series.get(key)
                if s is None:
                    s = _timeseries._Series(metric, stat, labels, kind,
                                            self.tier_spec, self.interval)
                    self._series[key] = s
                if isinstance(raw, tuple):
                    value = s.rate.observe(float(raw[1]), now)
                else:
                    value = _timeseries._finite_or_none(raw)
                s.push(now, value)
                out.append((metric, stat, labels, value))
        return out

    def ingest(self, rank, metrics, now, prefixes=()):
        """Merge one scraped ``/allz`` metrics snapshot under the given
        rank label.  Histogram samples become client-side p50/p99 (None
        = overflow) plus a count rate; counters become rates; gauges
        keep their value.  Returns the pushed rows."""
        rows = []
        for name in sorted(metrics):
            if prefixes and not any(name.startswith(p) for p in prefixes):
                continue
            fam = metrics[name]
            kind = fam.get("type", "gauge")
            for sample in fam.get("samples", ()):
                labels = dict(sample.get("labels") or {})
                labels["rank"] = rank
                if kind == "histogram":
                    for stat, q in self.QUANTILES:
                        rows.append((name, stat, labels, kind,
                                     quantile_from_buckets(sample, q)))
                    rows.append((name, "rate", labels, kind,
                                 ("counter",
                                  float(sample.get("count") or 0))))
                elif kind == "counter":
                    rows.append((name, "rate", labels, kind,
                                 ("counter",
                                  float(sample.get("value") or 0.0))))
                else:
                    rows.append((name, "value", labels, kind,
                                 sample.get("value")))
        return self.push_rows(rows, now)

    # -- readers -----------------------------------------------------------

    def snapshot(self, window_seconds=None, prefix=None, now=None):
        """JSON-able {series_key: {metric, stat, labels, kind, tiers}} —
        same shape as ``TimeSeriesStore.snapshot`` so the rendering
        helpers (sparklines, ``render_ascii``) apply unchanged."""
        now = time.time() if now is None else float(now)
        with self._lock:
            items = sorted(self._series.items())
        out = {}
        for key, s in items:
            if prefix and not s.metric.startswith(prefix):
                continue
            out[key] = {"metric": s.metric, "stat": s.stat,
                        "labels": s.labels, "kind": s.kind,
                        "tiers": [t.as_dict(window_seconds, now)
                                  for t in s.tiers]}
        return out

    def latest(self, metric, stat, rank):
        """Newest non-None finest-tier value of the exact series
        ``metric:stat{rank=rank}`` (no other labels), or None."""
        key = _timeseries.series_key(metric, stat, {"rank": rank})
        with self._lock:
            s = self._series.get(key)
            pts = list(s.tiers[0].points) if s is not None else []
        for _, v in reversed(pts):
            if v is not None:
                return v
        return None

    def window_stats(self, metric, stat, rank, window, now):
        """(mean, oldest_t, n) over finite finest-tier points of the
        exact series ``metric:stat{rank=rank}`` within ``window``."""
        key = _timeseries.series_key(metric, stat, {"rank": rank})
        with self._lock:
            s = self._series.get(key)
            pts = list(s.tiers[0].points) if s is not None else []
        cut = now - float(window)
        vals = [(t, v) for t, v in pts if t >= cut and v is not None]
        if not vals:
            return None, None, 0
        return (sum(v for _, v in vals) / len(vals), vals[0][0], len(vals))

    def ranks_of(self, metric, stat):
        """Rank labels (excluding the synthetic "fleet" rank) holding
        the exact series ``metric:stat{rank=R}``."""
        with self._lock:
            series = list(self._series.values())
        out = []
        for s in series:
            if (s.metric == metric and s.stat == stat
                    and set(s.labels) == {"rank"}
                    and s.labels["rank"] != "fleet"):
                out.append(s.labels["rank"])
        return sorted(out)

    def clear(self):
        with self._lock:
            self._series.clear()

    def __len__(self):
        with self._lock:
            return len(self._series)


# ---------------------------------------------------------------------------
# declarative alert rules
# ---------------------------------------------------------------------------

_OPS = {">": lambda a, b: a > b, "<": lambda a, b: a < b}


class AlertRule:
    """One declarative alert over a merged fleet (or per-rank) series.

    ``kind``:

    - ``threshold`` — newest value ``op`` threshold;
    - ``delta`` — short-window mean collapsed below
      ``(1 - drop_frac) x`` the long-window mean;
    - ``absence`` — a registered target has not been scraped
      successfully for ``threshold`` seconds;
    - ``burn_rate`` — the classic multi-window burn rate: the mean over
      *both* the short and the long window satisfies ``op`` threshold
      (the long window needs >= half its span of data, so one hiccup
      cannot page).

    ``scope`` is ``"fleet"`` (evaluate the synthetic ``rank="fleet"``
    aggregate series) or ``"rank"`` (evaluate every rank's own series;
    each rank is its own alert instance and its own offender).
    ``offender`` names a per-rank derived column (``step_seconds``,
    ``mfu_pct``, ``hbm_bytes``, ``hbm_frac``) whose argmax picks the
    rank to blame — and, for page severity, whose flight-recorder dump
    trigger is POSTed at fire time.  The registered rule set is
    documented in the GL-checked table in docs/observability.md."""

    def __init__(self, name, kind, severity="warn", metric=None,
                 stat="value", scope="fleet", op=">", threshold=None,
                 windows=None, drop_frac=0.5, offender=None, help=""):  # noqa: A002
        if kind not in ("threshold", "delta", "absence", "burn_rate"):
            raise ValueError("unknown alert kind %r" % kind)
        if severity not in _SEVERITIES:
            raise ValueError("severity must be one of %r" % (_SEVERITIES,))
        if op not in _OPS:
            raise ValueError("op must be one of %r" % list(_OPS))
        if kind != "absence" and not metric:
            raise ValueError("%s rule needs a metric" % kind)
        if kind in ("delta", "burn_rate") and not windows:
            raise ValueError("%s rule needs (short, long) windows" % kind)
        self.name = name
        self.kind = kind
        self.severity = severity
        self.metric = metric
        self.stat = stat
        self.scope = scope
        self.op = op
        self.threshold = threshold
        self.windows = tuple(windows) if windows else None
        self.drop_frac = float(drop_frac)
        self.offender = offender
        self.help = help

    def as_dict(self):
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "metric": self.metric,
                "stat": self.stat, "scope": self.scope, "op": self.op,
                "threshold": self.threshold, "windows": self.windows,
                "drop_frac": self.drop_frac, "offender": self.offender,
                "help": self.help}

    def conditions(self, store, now):
        """Yield ``(group, value, firing)`` per alert instance (absence
        rules are evaluated by the collector, which owns the target
        table)."""
        if self.kind == "absence":
            return
        groups = (["fleet"] if self.scope == "fleet"
                  else store.ranks_of(self.metric, self.stat))
        op = _OPS[self.op]
        for group in groups:
            if self.kind == "threshold":
                v = store.latest(self.metric, self.stat, group)
                yield (group, v,
                       v is not None and op(v, self.threshold))
            elif self.kind == "delta":
                short, long_ = self.windows
                s_mean, _, _ = store.window_stats(
                    self.metric, self.stat, group, short, now)
                l_mean, l_old, l_n = store.window_stats(
                    self.metric, self.stat, group, long_, now)
                covered = (l_n >= 2 and l_old is not None
                           and l_old <= now - 0.5 * long_)
                firing = (covered and s_mean is not None
                          and l_mean is not None and l_mean > 0
                          and s_mean < (1.0 - self.drop_frac) * l_mean)
                ratio = (s_mean / l_mean
                         if s_mean is not None and l_mean else None)
                yield (group, ratio, firing)
            else:  # burn_rate
                short, long_ = self.windows
                s_mean, _, s_n = store.window_stats(
                    self.metric, self.stat, group, short, now)
                l_mean, l_old, l_n = store.window_stats(
                    self.metric, self.stat, group, long_, now)
                covered = (s_n >= 1 and l_n >= 2 and l_old is not None
                           and l_old <= now - 0.5 * long_)
                firing = (covered and op(s_mean, self.threshold)
                          and op(l_mean, self.threshold))
                yield (group, s_mean, firing)


def default_rules():
    """The built-in rule set (thresholds resolved from the environment
    at call time; see the rule table in docs/observability.md)."""
    short = get_env("MXNET_FLEET_BURN_SHORT", 60.0, float)
    long_ = get_env("MXNET_FLEET_BURN_LONG", 300.0, float)
    return [
        AlertRule("straggler_skew_burn", kind="burn_rate", severity="page",
                  metric="fleet_straggler_skew",
                  threshold=get_env("MXNET_FLEET_SKEW_THRESHOLD", 1.75,
                                    float),
                  windows=(short, long_), offender="step_seconds",
                  help="sustained straggler: max/median step time above "
                       "the band over both burn windows"),
        AlertRule("scrape_absence", kind="absence", severity="warn",
                  threshold=get_env("MXNET_FLEET_ABSENCE_AFTER", 15.0,
                                    float),
                  help="a registered target has not answered a scrape"),
        AlertRule("fleet_mfu_drop", kind="delta", severity="warn",
                  metric="fleet_mfu_pct",
                  drop_frac=get_env("MXNET_FLEET_MFU_DROP", 0.5, float),
                  windows=(short, long_),
                  help="fleet MFU collapsed vs its long-window mean"),
        AlertRule("hbm_pressure", kind="threshold", severity="page",
                  metric="fleet_hbm_used_frac",
                  threshold=get_env("MXNET_FLEET_HBM_FRAC", 0.95, float),
                  offender="hbm_frac",
                  help="worst rank is close to its HBM limit"),
    ]


_rules_lock = threading.Lock()
_rules: Dict[str, AlertRule] = {r.name: r for r in default_rules()}


def register_rule(rule: AlertRule, replace=False):
    """Register an alert rule (module-level, like telemetry metrics).
    Re-registering an existing name requires ``replace=True``."""
    with _rules_lock:
        if rule.name in _rules and not replace:
            raise ValueError("alert rule %r already registered"
                             % rule.name)
        _rules[rule.name] = rule
    return rule


def rules() -> List[AlertRule]:
    with _rules_lock:
        return list(_rules.values())


def reset_rules():
    """Reinstall the default rule set (re-reading env thresholds)."""
    fresh = {r.name: r for r in default_rules()}
    with _rules_lock:
        _rules.clear()
        _rules.update(fresh)


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------

def _post_flight_trigger(endpoint, reason, timeout):
    """POST the target's /flightz dump trigger; returns the dump path
    the target reports (its filesystem, not ours)."""
    url = "http://%s:%d/flightz?reason=%s" % (
        endpoint.get("host", "127.0.0.1"), int(endpoint["port"]),
        urllib.parse.quote(str(reason), safe=""))
    req = urllib.request.Request(url, data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace")).get("path")


class FleetCollector(threading.Thread):
    """Daemon scrape/merge/alert loop over one fleet directory."""

    def __init__(self, fleet_dir=None, interval=None, timeout=None,
                 stale_after=None, debounce=None, prefixes=None,
                 window=300.0):
        super().__init__(name="mxtpu-fleet-collector", daemon=True)
        if fleet_dir is None:
            fleet_dir = get_env("MXNET_FLEET_DIR", None)
        if not fleet_dir:
            raise ValueError("fleet collector needs a fleet directory "
                             "(MXNET_FLEET_DIR)")
        self.fleet_dir = fleet_dir
        self.interval = float(
            get_env("MXNET_FLEET_SCRAPE_INTERVAL", 5.0, float)
            if interval is None else interval)
        self.timeout = float(
            get_env("MXNET_FLEET_SCRAPE_TIMEOUT", 2.0, float)
            if timeout is None else timeout)
        self.stale_after = float(
            get_env("MXNET_FLEET_STALE_AFTER", 30.0, float)
            if stale_after is None else stale_after)
        self.debounce = float(
            get_env("MXNET_FLEET_ALERT_DEBOUNCE", 60.0, float)
            if debounce is None else debounce)
        raw = (get_env("MXNET_FLEET_METRIC_PREFIXES", _DEFAULT_PREFIXES)
               if prefixes is None else prefixes)
        if isinstance(raw, str):
            self.prefixes = tuple(p for p in raw.split(",") if p)
        else:
            self.prefixes = tuple(raw)
        self.window = float(window)
        self.store = FleetStore(self.interval)
        self._lock = threading.Lock()
        self._targets: Dict[str, dict] = {}
        self._alert_state: Dict[Tuple[str, str], dict] = {}
        self._history: collections.deque = collections.deque(maxlen=64)
        self._last_aggregates: dict = {}
        self._stop_evt = threading.Event()

    # -- thread ------------------------------------------------------------

    def run(self):
        while not self._stop_evt.wait(self.interval):
            try:
                self.sweep()
            except Exception:
                _SCRAPE_ERRS.labels(target="collector").inc()

    def halt(self, timeout: float = 5.0):
        self._stop_evt.set()
        self.join(timeout)

    # -- one tick ----------------------------------------------------------

    def _fetch_allz(self, endpoint):
        url = "http://%s:%d/allz?window=%g" % (
            endpoint.get("host", "127.0.0.1"), int(endpoint["port"]),
            max(self.interval * 3.0, 30.0))
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))

    def sweep(self, now=None):
        """One scrape/merge/derive/alert tick (also driven directly by
        tests and the smoke probe)."""
        now = time.time() if now is None else float(now)
        endpoints = discover(self.fleet_dir, stale_after=self.stale_after,
                             reap=True, now=now)
        _TARGETS.set(len(endpoints))
        with self._lock:
            for tid in list(self._targets):
                if tid not in endpoints:
                    del self._targets[tid]  # reaped: drop its state
            for tid, ep in endpoints.items():
                t = self._targets.get(tid)
                if t is None:
                    self._targets[tid] = t = {
                        "endpoint": ep, "first_seen": now, "last_ok": None,
                        "consecutive_errors": 0, "skip_until": 0.0,
                        "healthz": None, "rows": []}
                else:
                    t["endpoint"] = ep
            todo = [(tid, dict(t["endpoint"]))
                    for tid, t in sorted(self._targets.items())
                    if now >= t["skip_until"]]
        for tid, ep in todo:
            t0 = time.time()
            try:
                doc = self._fetch_allz(ep)
                rows = self.store.ingest(tid, doc.get("metrics") or {},
                                         now, self.prefixes)
            except Exception:
                _SCRAPE_ERRS.labels(target=tid).inc()
                with self._lock:
                    t = self._targets.get(tid)
                    if t is not None:
                        t["consecutive_errors"] += 1
                        # exponential backoff in whole ticks, capped
                        skip = min(2 ** (t["consecutive_errors"] - 1), 8)
                        t["skip_until"] = now + self.interval * (skip - 1)
                continue
            _SCRAPES.labels(target=tid).inc()
            _SCRAPE_TIME.labels(target=tid).observe(time.time() - t0)
            with self._lock:
                t = self._targets.get(tid)
                if t is not None:
                    t["last_ok"] = now
                    t["consecutive_errors"] = 0
                    t["skip_until"] = 0.0
                    t["healthz"] = doc.get("healthz")
                    t["rows"] = rows
        per_rank = self._derive(now)
        self._evaluate(per_rank, now)
        return per_rank

    # -- derived fleet aggregates ------------------------------------------

    def _derive(self, now):
        with self._lock:
            snap = {tid: {"rows": list(t["rows"]), "last_ok": t["last_ok"],
                          "role": t["endpoint"].get("role", "worker"),
                          "healthz": t["healthz"]}
                    for tid, t in self._targets.items()}
        per_rank: Dict[str, dict] = {}
        owners: Dict[str, float] = {}
        models: Dict[str, dict] = {}
        p99s: List[Optional[float]] = []
        for tid, t in sorted(snap.items()):
            if t["last_ok"] is None or now - t["last_ok"] > self.stale_after:
                continue
            hz = t["healthz"] or {}
            pr = {"role": t["role"], "step_seconds": None, "mfu_pct": None,
                  "hbm_bytes": 0.0, "hbm_limit": 0.0, "hbm_frac": None,
                  "verdict": hz.get("cause"), "status": hz.get("status")}
            for metric, stat, labels, value in t["rows"]:
                if metric == "serving_request_seconds" and stat == "p99":
                    p99s.append(value)  # None = off-scale tail
                    continue
                if value is None:
                    continue
                if metric == "step_seconds_ewma" and stat == "value":
                    pr["step_seconds"] = value
                elif metric == "step_mfu_pct" and stat == "value":
                    pr["mfu_pct"] = value
                elif metric == "device_bytes_in_use" and stat == "value":
                    pr["hbm_bytes"] += value
                elif metric == "device_bytes_limit" and stat == "value":
                    pr["hbm_limit"] += value
                elif metric == "memwatch_owner_bytes" and stat == "value":
                    owner = labels.get("owner", "?")
                    owners[owner] = owners.get(owner, 0.0) + value
                elif (metric == "serving_model_requests_total"
                      and stat == "rate" and value > 0):
                    m = models.setdefault(labels.get("model", "?"),
                                          {"qps": 0.0, "shed_rate": 0.0})
                    if labels.get("outcome") == "ok":
                        m["qps"] += value
                    elif labels.get("outcome") == "rejected":
                        m["shed_rate"] += value
            if pr["hbm_limit"] > 0:
                pr["hbm_frac"] = pr["hbm_bytes"] / pr["hbm_limit"]
            per_rank[tid] = pr

        steps = [pr["step_seconds"] for pr in per_rank.values()
                 if pr["step_seconds"]]
        step_rate = sum(1.0 / s for s in steps) if steps else None
        skew = None
        if len(steps) >= 2:
            med = _median(steps)
            if med > 0:
                skew = max(steps) / med
        mfus = [pr["mfu_pct"] for pr in per_rank.values()
                if pr["mfu_pct"] is not None]
        mfu = sum(mfus) / len(mfus) if mfus else None
        fracs = [pr["hbm_frac"] for pr in per_rank.values()
                 if pr["hbm_frac"] is not None]
        hbm_frac = max(fracs) if fracs else None
        p99 = None
        if p99s:
            p99 = None if any(v is None for v in p99s) else max(p99s)

        # the synthetic rank="fleet" series the rules + dashboard read
        fleet_rows = [
            ("fleet_step_rate", "value", {"rank": "fleet"}, "gauge",
             step_rate),
            ("fleet_mfu_pct", "value", {"rank": "fleet"}, "gauge", mfu),
            ("fleet_straggler_skew", "value", {"rank": "fleet"}, "gauge",
             skew),
            ("fleet_hbm_used_frac", "value", {"rank": "fleet"}, "gauge",
             hbm_frac),
        ]
        if p99s:
            fleet_rows.append(("fleet_serving_p99_seconds", "p99",
                               {"rank": "fleet"}, "gauge", p99))
        self.store.push_rows(fleet_rows, now)

        # local gauges (served on this process's /metrics)
        if step_rate is not None:
            _STEP_RATE.set(step_rate)
        if mfu is not None:
            _FLEET_MFU.set(mfu)
        if skew is not None:
            _SKEW.set(skew)
        if hbm_frac is not None:
            _HBM_FRAC.set(hbm_frac)
        if p99s:
            _SERVING_P99.set(float("nan") if p99 is None else p99)
        for owner, b in owners.items():
            _HBM_OWNER.labels(owner=owner).set(b)
        for tid, pr in per_rank.items():
            _RANK_HBM.labels(rank=tid).set(pr["hbm_bytes"])
        for m, d in models.items():
            _MODEL_QPS.labels(model=m).set(d["qps"])
            _MODEL_SHED.labels(model=m).set(d["shed_rate"])

        aggregates = {"step_rate": step_rate, "mfu_pct": mfu,
                      "straggler_skew": skew, "hbm_used_frac": hbm_frac,
                      "hbm_owner_bytes": owners,
                      "serving_p99_seconds": p99,
                      "serving_p99_off_scale": bool(p99s) and p99 is None,
                      "models": models, "per_rank": per_rank}
        with self._lock:
            self._last_aggregates = aggregates
        return per_rank

    # -- alert evaluation --------------------------------------------------

    def _evaluate(self, per_rank, now):
        fires, resolves = [], []
        for rule in rules():
            if rule.kind == "absence":
                with self._lock:
                    conds = [(tid,
                              now - (t["last_ok"] or t["first_seen"]),
                              (now - (t["last_ok"] or t["first_seen"]))
                              > rule.threshold)
                             for tid, t in sorted(self._targets.items())]
            else:
                conds = list(rule.conditions(self.store, now))
            for group, value, firing in conds:
                key = (rule.name, group)
                with self._lock:
                    st = self._alert_state.setdefault(
                        key, {"firing": False, "last_fire": 0.0,
                              "value": None, "severity": rule.severity})
                    st["value"] = value
                    if (firing and not st["firing"]
                            and now - st["last_fire"] >= self.debounce):
                        st["firing"] = True
                        st["last_fire"] = now
                        fires.append((rule, group, value))
                    elif not firing and st["firing"]:
                        st["firing"] = False
                        resolves.append((rule, group))
        # actions run with no collector lock held (HTTP + runlog I/O)
        for rule, group, value in fires:
            self._fire(rule, group, value, per_rank, now)
        for rule, group in resolves:
            self._resolve(rule, group)
        active = {sev: 0 for sev in _SEVERITIES}
        with self._lock:
            for st in self._alert_state.values():
                if st["firing"]:
                    active[st.get("severity", "warn")] += 1
        for sev in _SEVERITIES:
            _ALERTS_ACTIVE.labels(severity=sev).set(active[sev])

    def _fire(self, rule, group, value, per_rank, now):
        _ALERTS_TOTAL.labels(rule=rule.name, severity=rule.severity).inc()
        if rule.scope == "rank" or rule.kind == "absence":
            offender = group
        elif rule.offender:
            best = None
            for tid, pr in per_rank.items():
                v = pr.get(rule.offender)
                if v is not None and (best is None or v > best[1]):
                    best = (tid, v)
            offender = best[0] if best else None
        else:
            offender = None
        dump_path = None
        if (rule.severity == "page" and offender
                and rule.kind != "absence"):
            with self._lock:
                t = self._targets.get(offender)
                ep = dict(t["endpoint"]) if t else None
            if ep:
                try:
                    dump_path = _post_flight_trigger(
                        ep, "fleet_alert." + rule.name, self.timeout)
                except Exception:
                    dump_path = None  # the page still goes out
        rec = {"rule": rule.name, "severity": rule.severity,
               "kind": rule.kind, "group": group, "value": value,
               "threshold": rule.threshold, "offender": offender,
               "flight_dump": dump_path, "unix_time": now}
        with self._lock:
            self._history.append(rec)
        try:
            from .. import runlog as _runlog
            _runlog.event("fleet_alert", rule=rule.name,
                          severity=rule.severity, group=group, value=value,
                          threshold=rule.threshold, offender=offender,
                          flight_dump=dump_path)
        except Exception:
            pass

    def _resolve(self, rule, group):
        try:
            from .. import runlog as _runlog
            _runlog.event("fleet_alert_resolved", rule=rule.name,
                          group=group)
        except Exception:
            pass

    # -- readers -----------------------------------------------------------

    def active_alerts(self):
        with self._lock:
            state = {k: dict(st) for k, st in self._alert_state.items()}
        return [{"rule": name, "group": group,
                 "severity": st.get("severity", "warn"),
                 "value": st["value"], "since": st["last_fire"]}
                for (name, group), st in sorted(state.items())
                if st["firing"]]

    def fleetz_doc(self, window=None, now=None):
        """The merged fleet view served on /fleetz (and consumed by
        tools/fleetwatch.py)."""
        now = time.time() if now is None else float(now)
        window = self.window if window is None else float(window)
        with self._lock:
            targets = {}
            for tid, t in sorted(self._targets.items()):
                ep = t["endpoint"]
                targets[tid] = {
                    "rank": ep.get("rank"), "role": ep.get("role"),
                    "pid": ep.get("pid"), "host": ep.get("host"),
                    "port": ep.get("port"), "run_id": ep.get("run_id"),
                    "last_ok_age_seconds":
                        (now - t["last_ok"]) if t["last_ok"] else None,
                    "consecutive_errors": t["consecutive_errors"],
                    "healthz": t["healthz"]}
            aggregates = dict(self._last_aggregates)
            recent = list(self._history)
        return {"unix_time": now, "interval": self.interval,
                "fleet_dir": self.fleet_dir, "targets": targets,
                "aggregates": aggregates,
                "alerts": {"active": self.active_alerts(),
                           "recent": recent},
                "rules": [r.as_dict() for r in rules()],
                "series": self.store.snapshot(window_seconds=window,
                                              now=now)}

    def flight_block(self, now=None):
        """Bounded fleet context for this process's flight dumps: the
        target table, derived aggregates and alert state — no ring
        history (the per-rank evidence lives in the offending rank's
        own dump)."""
        doc = self.fleetz_doc(window=0.0, now=now)
        doc.pop("series", None)
        doc.pop("rules", None)
        return doc


# ---------------------------------------------------------------------------
# module singleton
# ---------------------------------------------------------------------------

_collector: Optional[FleetCollector] = None
_collector_lock = threading.Lock()


def start_collector(fleet_dir=None, interval=None, **kwargs):
    """Start (or return the already-running) fleet collector daemon."""
    global _collector
    with _collector_lock:
        if _collector is not None and _collector.is_alive():
            return _collector
        c = FleetCollector(fleet_dir=fleet_dir, interval=interval,
                           **kwargs)
        _collector = c
    c.start()
    return c


def stop_collector():
    """Stop the collector thread (merged rings are dropped with it)."""
    global _collector
    with _collector_lock:
        c, _collector = _collector, None
    if c is not None:
        c.halt()


def running() -> bool:
    with _collector_lock:
        return _collector is not None and _collector.is_alive()


def collector() -> Optional[FleetCollector]:
    with _collector_lock:
        return _collector


def fleetz(window=None):
    """The merged fleet view, or None when no collector is running."""
    c = collector()
    return c.fleetz_doc(window=window) if c is not None else None


def flight_block():
    """Fleet block for flight dumps (None when not collecting)."""
    c = collector()
    return c.flight_block() if c is not None else None


def reset():
    """Test isolation: stop the collector, drop the endpoint
    registration and reinstall the default rules."""
    stop_collector()
    unregister_endpoint()
    reset_rules()
