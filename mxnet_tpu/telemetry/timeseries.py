"""Time-series telemetry: multi-resolution history of the live registry.

The registry answers "what is the process doing *right now*"; everything
before the current scrape evaporates.  This module gives it a memory in
the RRDtool/Prometheus-TSDB mold, sized for an always-on runtime rather
than a database: a background sampler snapshots every registered metric
on a cadence (``MXNET_TELEMETRY_TS_INTERVAL``, default 1 s) into fixed
multi-resolution ring buffers — 512 points at 1× the sampling interval,
512 at 10×, 512 at 60× (≈8.5 min / 85 min / 8.5 h of trailing history at
the 1 s default) — so a flight-recorder dump or a ``/timeseriesz``
scrape can show the minutes *leading up to* an anomaly, not just the
instant after it.

What is stored per series (one point per tier step, mean-aggregated
into the coarser tiers):

- **counters** → a windowed rate (:class:`registry.WindowedRate` — the
  one shared rate definition, so "ops/s" here matches any dashboard
  computing it the same way), under the ``rate`` stat;
- **gauges** → the sampled value (``value``);
- **histograms** → ``p50`` / ``p99`` via the existing
  :meth:`Histogram.quantile` plus an observation-count ``rate``.

Quantiles that fall in the +Inf overflow bucket are stored as ``None``
(JSON ``null``) — an off-scale tail must read as "off scale", and
``json.dumps`` would otherwise emit non-standard ``Infinity``.

Cost model: sampling reads counters/gauges/bucket arrays under the
per-family metric locks the increment path already uses — pure host
arithmetic, no jax calls, so the sampler adds **zero** XLA compiles by
construction, and its steady-state cost is one registry walk per
interval off the training thread (bench.py A/Bs the residual as
``sampler_overhead_pct``).  Nothing is sampled (and no thread exists)
until :func:`start` — which ``telemetry.enable()`` calls unless
``MXNET_TELEMETRY_TS=0``.

Lock discipline (graftlint GL003): samples are *computed* outside the
store lock and appended under it; the sampler thread sleeps via
``Event.wait(timeout)`` and is joined with a timeout, never while any
telemetry lock is held.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import get_env
from .registry import Histogram, MetricRegistry, WindowedRate

__all__ = ["TimeSeriesStore", "DEFAULT_TIERS", "series_key", "sparkline",
           "render_ascii", "store", "start", "stop", "running",
           "snapshot", "trailing"]

#: (base-sample multiplier, ring capacity) per tier, finest first.  A
#: tier emits one point per ``multiplier`` base samples (the mean of the
#: non-None samples in that window), so tier spans are exact multiples
#: of the sampling interval regardless of wall-clock jitter.
DEFAULT_TIERS: Tuple[Tuple[int, int], ...] = ((1, 512), (10, 512), (60, 512))

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[Optional[float]], width: int = 64) -> str:
    """Unicode sparkline of ``values`` (None renders as a gap).  Keeps
    the newest ``width`` points; scaled min..max over the shown finite
    points so shape, not magnitude, is what reads."""
    vals = list(values)[-width:]
    finite = [v for v in vals if v is not None and math.isfinite(v)]
    if not finite:
        return " " * len(vals)
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if v is None or not math.isfinite(v):
            out.append(" ")
        elif span <= 0:
            out.append(_SPARK_BLOCKS[0])
        else:
            out.append(_SPARK_BLOCKS[int((v - lo) / span
                                         * (len(_SPARK_BLOCKS) - 1))])
    return "".join(out)


def _finite_or_none(v) -> Optional[float]:
    if v is None:
        return None
    v = float(v)
    return v if math.isfinite(v) else None


class _Tier:
    """One resolution's ring buffer plus the open aggregation window that
    rolls ``every`` base samples up into one (t, mean) point."""

    __slots__ = ("resolution", "every", "points", "_acc_sum", "_acc_n",
                 "_seen")

    def __init__(self, resolution: float, capacity: int, every: int):
        self.resolution = resolution
        self.every = max(1, int(every))
        self.points: deque = deque(maxlen=capacity)
        self._acc_sum = 0.0
        self._acc_n = 0
        self._seen = 0

    def push(self, t: float, value: Optional[float]):
        self._seen += 1
        if value is not None:
            self._acc_sum += value
            self._acc_n += 1
        if self._seen >= self.every:
            mean = (self._acc_sum / self._acc_n) if self._acc_n else None
            self.points.append((t, mean))
            self._acc_sum, self._acc_n, self._seen = 0.0, 0, 0

    def as_dict(self, window_seconds: Optional[float] = None,
                now: Optional[float] = None) -> Dict[str, object]:
        pts = list(self.points)
        if window_seconds is not None and now is not None:
            cut = now - window_seconds
            pts = [p for p in pts if p[0] >= cut]
        return {"resolution": self.resolution,
                "points": [[round(t, 3), v] for t, v in pts]}


class _Series:
    __slots__ = ("metric", "stat", "labels", "kind", "tiers", "rate")

    def __init__(self, metric, stat, labels, kind, tier_spec, interval):
        self.metric = metric
        self.stat = stat
        self.labels = dict(labels)
        self.kind = kind
        self.tiers = [_Tier(interval * mult, cap, mult)
                      for mult, cap in tier_spec]
        self.rate = WindowedRate()  # drives counter / hist-count series

    def push(self, t: float, value: Optional[float]):
        for tier in self.tiers:
            tier.push(t, value)


def series_key(metric: str, stat: str, labelvalues: Dict[str, str]) -> str:
    lbl = ",".join("%s=%s" % kv for kv in sorted(labelvalues.items()))
    return "%s:%s{%s}" % (metric, stat, lbl) if lbl \
        else "%s:%s" % (metric, stat)


class TimeSeriesStore:
    """Per-series multi-resolution rings over one :class:`MetricRegistry`.

    ``sample_once`` is the whole data path: walk the registry, derive
    each series' sample (rate / value / quantiles) with no lock of this
    store held, then append under the store lock.
    """

    #: histogram quantile stats sampled per series.
    QUANTILES = (("p50", 0.5), ("p99", 0.99))

    def __init__(self, registry: MetricRegistry,
                 interval: float = 1.0,
                 tiers: Sequence[Tuple[int, int]] = DEFAULT_TIERS):
        self.registry = registry
        self.interval = float(interval)
        self.tier_spec = tuple(tiers)
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        # bound name carries "telemetry" so graftlint GL005 attributes
        # these registrations to the metric registry contract
        telemetry_registry = registry
        self._m_samples = telemetry_registry.counter(
            "timeseries_samples_total",
            "registry sampling sweeps completed by the time-series store")
        self._m_errors = telemetry_registry.counter(
            "timeseries_sample_errors_total",
            "sampling sweeps aborted by an unexpected error")
        self._m_series = telemetry_registry.gauge(
            "timeseries_series",
            "distinct series currently held in the time-series rings")

    # -- sampling ----------------------------------------------------------
    def _samples_of(self, fam) -> List[Tuple[str, str, Dict[str, str],
                                             object]]:
        """(stat, key, labels, raw) rows for one family's children; raw
        is ('counter', cumulative) for rate-derived series."""
        rows = []
        for labelvalues, data in fam.samples():
            labels = dict(zip(fam.labelnames, labelvalues))
            if isinstance(fam, Histogram):
                child = fam.labels(**labels)
                for stat, q in self.QUANTILES:
                    rows.append((stat, series_key(fam.name, stat, labels),
                                 labels, child.quantile(q)))
                rows.append(("rate", series_key(fam.name, "rate", labels),
                             labels, ("counter", float(data["count"]))))
            elif fam.kind == "counter":
                rows.append(("rate", series_key(fam.name, "rate", labels),
                             labels, ("counter", float(data))))
            else:  # gauge
                rows.append(("value", series_key(fam.name, "value", labels),
                             labels, float(data)))
        return rows

    def sample_once(self, now: Optional[float] = None) -> int:
        """Sample every registered series once; returns the number of
        series touched.  Safe to call concurrently with increments (the
        family locks serialize reads) and with itself (store lock)."""
        now = time.time() if now is None else float(now)
        staged = []
        for fam in self.registry.collect():
            for stat, key, labels, raw in self._samples_of(fam):
                staged.append((fam, stat, key, labels, raw))
        n = 0
        with self._lock:
            for fam, stat, key, labels, raw in staged:
                series = self._series.get(key)
                if series is None:
                    series = _Series(fam.name, stat, labels, fam.kind,
                                     self.tier_spec, self.interval)
                    self._series[key] = series
                if isinstance(raw, tuple):   # cumulative counter -> rate
                    value = series.rate.observe(raw[1], now)
                else:
                    value = _finite_or_none(raw)
                series.push(now, value)
                n += 1
            n_series = len(self._series)
        self._m_samples.inc()
        self._m_series.set(n_series)
        return n

    # -- readers -----------------------------------------------------------
    def snapshot(self, window_seconds: Optional[float] = None,
                 prefix: Optional[str] = None,
                 now: Optional[float] = None) -> Dict[str, dict]:
        """JSON-able {series_key: {metric, stat, labels, kind, tiers}}.

        ``window_seconds`` bounds each tier's points; ``prefix`` filters
        by metric-name prefix."""
        now = time.time() if now is None else float(now)
        with self._lock:
            items = sorted(self._series.items())
        out = {}
        for key, s in items:
            if prefix and not s.metric.startswith(prefix):
                continue
            out[key] = {
                "metric": s.metric, "stat": s.stat, "labels": s.labels,
                "kind": s.kind,
                "tiers": [t.as_dict(window_seconds, now) for t in s.tiers],
            }
        return out

    def trailing(self, window_seconds: float = 120.0,
                 now: Optional[float] = None) -> Dict[str, object]:
        """The flight-dump block: per series, the last ``window_seconds``
        from the finest tier, extended backwards with coarser-tier points
        when the fine ring alone does not reach the whole window (a
        long-lived process's 1 s ring covers ~8.5 min; beyond that the
        10 s / 60 s tiers carry the history)."""
        now = time.time() if now is None else float(now)
        cut = now - float(window_seconds)
        with self._lock:
            items = sorted(self._series.items())
        series = {}
        for key, s in items:
            pts: List[Tuple[float, Optional[float]]] = []
            for tier in s.tiers:           # finest first
                tier_pts = [p for p in tier.points if p[0] >= cut]
                if pts:
                    oldest = pts[0][0]
                    pts = [p for p in tier_pts if p[0] < oldest] + pts
                else:
                    pts = tier_pts
                if pts and pts[0][0] <= cut + tier.resolution:
                    break                  # window covered; stop coarsening
            if pts:
                series[key] = {"metric": s.metric, "stat": s.stat,
                               "labels": s.labels,
                               "points": [[round(t, 3), v] for t, v in pts]}
        return {"window_seconds": float(window_seconds),
                "interval": self.interval, "unix_time": now,
                "series": series}

    def clear(self):
        """Test isolation: drop every ring (rate trackers included)."""
        with self._lock:
            self._series.clear()

    def __len__(self):
        with self._lock:
            return len(self._series)


def render_ascii(snap: Dict[str, dict], width: int = 64) -> str:
    """Terminal rendering of a :meth:`TimeSeriesStore.snapshot`: one
    sparkline per series from its finest tier, newest value annotated."""
    lines = []
    for key in sorted(snap):
        tiers = snap[key].get("tiers") or []
        pts = (tiers[0].get("points") or []) if tiers else []
        vals = [p[1] for p in pts]
        last = next((v for v in reversed(vals) if v is not None), None)
        lines.append("%-56s %s  last=%s"
                     % (key[:56], sparkline(vals, width),
                        "-" if last is None else "%.6g" % last))
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# sampler thread + module-level singleton
# ---------------------------------------------------------------------------

class _Sampler(threading.Thread):
    """Daemon loop: one registry sweep per interval.  Sleeps on an Event
    so stop() is immediate; a sweep that raises is counted and skipped
    (telemetry must never take the process down)."""

    def __init__(self, ts_store: TimeSeriesStore):
        super().__init__(name="mxtpu-telemetry-ts", daemon=True)
        self._store = ts_store
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.wait(self._store.interval):
            try:
                self._store.sample_once()
            except Exception:
                self._store._m_errors.inc()

    def halt(self, timeout: float = 2.0):
        self._stop_evt.set()
        self.join(timeout)


_store: Optional[TimeSeriesStore] = None
_sampler: Optional[_Sampler] = None
_state_lock = threading.Lock()


def store() -> TimeSeriesStore:
    """The module singleton over the default telemetry registry
    (created on first use; no thread is started)."""
    global _store
    with _state_lock:
        if _store is None:
            from . import _registry
            _store = TimeSeriesStore(
                _registry,
                interval=get_env("MXNET_TELEMETRY_TS_INTERVAL", 1.0, float))
        return _store


def start(interval: Optional[float] = None) -> TimeSeriesStore:
    """Start (or return the already-running) background sampler over the
    default registry.  Idempotent; called by ``telemetry.enable()``."""
    global _sampler
    s = store()
    if interval is not None:
        s.interval = float(interval)
    with _state_lock:
        if _sampler is not None and _sampler.is_alive():
            return s
        _sampler = _Sampler(s)
        _sampler.start()
        return s


def stop():
    """Stop the sampler thread (rings are kept; ``store().clear()`` drops
    them).  Idempotent."""
    global _sampler
    with _state_lock:
        sampler, _sampler = _sampler, None
    if sampler is not None:
        sampler.halt(2.0)


def running() -> bool:
    with _state_lock:
        return _sampler is not None and _sampler.is_alive()


def snapshot(window_seconds: Optional[float] = None,
             prefix: Optional[str] = None) -> Dict[str, dict]:
    return store().snapshot(window_seconds=window_seconds, prefix=prefix)


def trailing(window_seconds: float = 120.0) -> Dict[str, object]:
    return store().trailing(window_seconds=window_seconds)
