"""Always-on runtime telemetry: metrics registry + exporters.

Reference analog: the reference profiles everything through the scheduler
(``ProfileOperator`` in ``threaded_engine.h`` plus the aggregate tables of
``aggregate_stats.cc``).  This package is that idea rebuilt in the
Prometheus/Dapper mold: a process-wide registry of ``Counter`` / ``Gauge``
/ ``Histogram`` instruments with label support, wired into the engine,
KVStore, data pipeline, executor and trainer, and exported as Prometheus
text exposition, a JSON snapshot, or an optional stdlib HTTP endpoint.

Relation to :mod:`mxnet_tpu.profiler`: the profiler answers "what happened
during this trace window" (Chrome-trace spans, bounded collection); the
telemetry registry answers "what is the process doing right now" (cheap
monotonic aggregates, safe to leave on in production).  They share one
timing path — ``profiler.span`` feeds a telemetry histogram when asked,
and ``profiler.Counter`` bridges its values into a registry gauge.

Cost model: the built-in instrumentation sites are gated by the module
attribute :data:`enabled` — a single attribute check on the disabled
(default) fast path, so bench numbers are unaffected.  Enable with
``MXNET_TELEMETRY=1`` in the environment or :func:`enable`; set
``MXNET_TELEMETRY_PORT`` to additionally serve ``/metrics``.

    from mxnet_tpu import telemetry
    telemetry.enable()
    ...train...
    print(telemetry.prometheus_text())
    telemetry.snapshot()["engine_ops_completed_total"]
"""
from __future__ import annotations

import sys as _sys

from ..base import get_env
from .registry import (Counter, Gauge, Histogram, MetricRegistry,
                       WindowedRate, DEFAULT_TIME_BUCKETS, log_buckets)
from . import export as _export

__all__ = ["enabled", "enable", "disable", "counter", "gauge", "histogram",
           "registry", "snapshot", "snapshot_json", "prometheus_text",
           "value", "quantile", "reset", "start_http_server",
           "stop_http_server", "timeseries",
           "Counter", "Gauge", "Histogram", "MetricRegistry",
           "WindowedRate", "DEFAULT_TIME_BUCKETS", "log_buckets"]

# The process-wide default registry.  Always live: instruments can be
# created and driven regardless of `enabled` (the flag only gates the
# built-in hot-path instrumentation sites).
_registry = MetricRegistry()

#: single-attribute-check gate read by the instrumentation sites
#: (``if _telemetry.enabled: ...``); default off.
enabled: bool = False


def registry() -> MetricRegistry:
    return _registry


def counter(name, help="", labelnames=()) -> Counter:  # noqa: A002
    """Get-or-create a counter in the default registry."""
    return _registry.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:  # noqa: A002
    return _registry.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(),  # noqa: A002
              buckets=None) -> Histogram:
    return _registry.histogram(name, help, labelnames, buckets=buckets)


def enable():
    """Turn the built-in instrumentation on; starts the /metrics endpoint
    when ``MXNET_TELEMETRY_PORT`` is set and the time-series sampler
    unless ``MXNET_TELEMETRY_TS=0``.  With ``MXNET_FLEET_DIR`` also set,
    the bound endpoint is announced in the fleet directory so a fleet
    collector can discover and scrape this process (see telemetry/fleet
    and docs/observability.md "Fleet")."""
    global enabled
    enabled = True
    port = get_env("MXNET_TELEMETRY_PORT", None, int)
    if port is not None:
        bound = start_http_server(port)
        if get_env("MXNET_FLEET_DIR", None):
            from . import fleet as _fleet
            _fleet.register_endpoint(bound)
    if get_env("MXNET_TELEMETRY_TS", True, bool):
        timeseries.start()


def disable():
    global enabled
    enabled = False
    timeseries.stop()
    if "mxnet_tpu.telemetry.fleet" in _sys.modules:
        _sys.modules["mxnet_tpu.telemetry.fleet"].unregister_endpoint()


def snapshot():
    """JSON-able dict of every metric (see export.snapshot)."""
    return _export.snapshot(_registry)


def snapshot_json(**kwargs) -> str:
    return _export.snapshot_json(_registry, **kwargs)


def prometheus_text() -> str:
    return _export.prometheus_text(_registry)


def value(name, **labels):
    """Convenience accessor: current value of one series (counters and
    gauges return the value; histograms return the observation count).
    Returns 0 for never-touched series so callers can test deltas."""
    fam = _registry.get(name)
    if fam is None:
        return 0
    child = fam.labels(**labels)
    data = child.get()
    if isinstance(data, dict):
        return data["count"]
    return data


def quantile(name, q, **labels):
    """Estimated q-quantile of one histogram series (bucket-interpolated;
    see _HistogramChild.quantile).  Returns 0.0 for unknown/never-observed
    series so callers can report without existence checks."""
    fam = _registry.get(name)
    if fam is None:
        return 0.0
    child = fam.labels(**labels)
    if not hasattr(child, "quantile"):
        return 0.0
    return child.quantile(q)


def reset():
    """Zero every recorded sample (test isolation)."""
    _registry.reset()


def start_http_server(port=None, host=None):
    """Explicitly start the /metrics endpoint (also reached via
    ``MXNET_TELEMETRY_PORT`` + enable()).  Returns the bound port."""
    if port is None:
        port = get_env("MXNET_TELEMETRY_PORT", 0, int)
    if host is None:
        host = get_env("MXNET_TELEMETRY_HOST", "127.0.0.1")
    return _export.start_http_server(int(port), _registry, host=host)


def stop_http_server():
    _export.stop_http_server()


# imported after _registry exists (timeseries.store() binds to it lazily)
from . import timeseries  # noqa: E402


if get_env("MXNET_TELEMETRY", False, bool):
    enable()
