"""Metric instruments and the process-wide registry.

Reference analog: the engine-side aggregate statistics of
``src/profiler/aggregate_stats.cc`` (per-op tables the reference keeps
always-on once profiling starts), redesigned in the Prometheus mold: a
process-wide registry of named ``Counter``/``Gauge``/``Histogram``
instruments with label support, scraped by the exporters in
:mod:`mxnet_tpu.telemetry.export`.

Threading model: one lock per metric family guards its child table AND
every child's value — increments arrive concurrently from the
ThreadedEngine worker pool, KVStore server handler threads, and data
pipeline producers.  Bound children (``metric.labels(...)``) are cached so
hot paths pay one dict lookup + one locked add per event.

The registry is always live: creating and incrementing instruments does
not depend on the global ``telemetry.enabled`` flag.  That flag only gates
the *built-in* instrumentation sites in engine/kvstore/io/executor, so the
default-off fast path stays a single attribute check.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["Counter", "Gauge", "Histogram", "MetricRegistry",
           "WindowedRate", "log_buckets", "DEFAULT_TIME_BUCKETS"]

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(lo: float = 1e-6, hi: float = 10.0,
                per_decade: int = 3) -> Tuple[float, ...]:
    """Fixed log-scale bucket bounds from ``lo`` to at least ``hi``
    (``per_decade`` bounds per power of ten).  The implicit +Inf bucket is
    appended by the Histogram itself."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise MXNetError("log_buckets: need 0 < lo < hi, per_decade >= 1")
    out: List[float] = []
    step = 10.0 ** (1.0 / per_decade)
    v = lo
    while v < hi * (1 + 1e-9):
        out.append(float("%.6g" % v))  # stable, readable bound labels
        v *= step
    return tuple(out)


# 1us .. ~21s in half-decade steps: wide enough for dispatch latencies and
# whole-epoch waits without per-instrument tuning.
DEFAULT_TIME_BUCKETS = log_buckets(1e-6, 20.0, per_decade=2)


class _Child:
    """One labelled time series of a metric family."""

    __slots__ = ("_family", "_labelvalues")

    def __init__(self, family: "_MetricFamily", labelvalues: Tuple[str, ...]):
        self._family = family
        self._labelvalues = labelvalues


class _CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, family, labelvalues):
        super().__init__(family, labelvalues)
        self._value = 0.0

    def _zero(self):
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise MXNetError("counter %r cannot decrease"
                             % self._family.name)
        with self._family._lock:
            self._value += amount

    def get(self) -> float:
        with self._family._lock:
            return self._value


class _GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, family, labelvalues):
        super().__init__(family, labelvalues)
        self._value = 0.0

    def _zero(self):
        self._value = 0.0

    def set(self, value: float):
        with self._family._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._family._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        with self._family._lock:
            self._value -= amount

    def get(self) -> float:
        with self._family._lock:
            return self._value


class _HistogramChild(_Child):
    __slots__ = ("_counts", "_sum", "_count")

    def __init__(self, family, labelvalues):
        super().__init__(family, labelvalues)
        # one slot per finite bound + the +Inf overflow slot
        self._counts = [0] * (len(family.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def _zero(self):
        self._counts = [0] * len(self._counts)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        value = float(value)
        if math.isnan(value):
            return  # a NaN sample would poison sum forever
        idx = bisect.bisect_left(self._family.buckets, value)
        with self._family._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def get(self) -> Dict[str, object]:
        """Snapshot: cumulative bucket counts keyed by upper bound."""
        with self._family._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        cum, out = 0, {}
        for bound, c in zip(self._family.buckets, counts):
            cum += c
            out["%g" % bound] = cum
        out["+Inf"] = cum + counts[-1]
        return {"buckets": out, "sum": s, "count": n}

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1) from the bucket counts, linearly
        interpolated inside the containing bucket.  Bucketed estimate —
        good to a half-decade, which is all p50/p99 dashboards need.
        Returns 0.0 with no observations and ``+inf`` when the target
        falls in the +Inf overflow bucket: the true value is beyond the
        top finite bound, and silently reporting that bound would make an
        off-scale tail look healthy.  Consumers that need a finite number
        (JSON without Infinity, sparklines) must handle it explicitly."""
        if not 0.0 <= q <= 1.0:
            raise MXNetError("quantile q must be in [0, 1], got %r" % q)
        with self._family._lock:
            counts = list(self._counts)
            n = self._count
        if n == 0:
            return 0.0
        bounds = self._family.buckets
        target = q * n
        cum = 0
        for i, c in enumerate(counts[:-1]):
            prev_cum = cum
            cum += c
            if cum >= target:
                hi = bounds[i]
                lo = bounds[i - 1] if i > 0 else 0.0
                frac = (target - prev_cum) / c if c else 0.0
                return lo + (hi - lo) * frac
        return float("inf")  # target falls in the +Inf overflow bucket


class WindowedRate:
    """THE windowed-rate definition for counters, shared by every consumer
    (the time-series sampler, dashboards, bench blocks) so "requests/s"
    means the same thing everywhere: ``(value - prev) / (now - prev_t)``
    between two cumulative observations.

    Counter resets (registry.reset(), process restart behind one store)
    surface as a *decrease*; the window restarts there and reports 0.0
    rather than a huge negative spike.  The first observation has no
    window and returns None.  Not thread-safe on its own: each consumer
    owns its tracker (the shared thing is the definition, not the state).
    """

    __slots__ = ("_prev_t", "_prev_v")

    def __init__(self):
        self._prev_t = None
        self._prev_v = None

    def observe(self, value: float, now: float) -> Optional[float]:
        """Feed one cumulative sample; returns the rate over the window
        since the previous sample (None for the first / a zero-length
        window, 0.0 across a counter reset)."""
        prev_t, prev_v = self._prev_t, self._prev_v
        self._prev_t, self._prev_v = float(now), float(value)
        if prev_t is None or now <= prev_t:
            return None
        if value < prev_v:        # counter reset: restart the window
            return 0.0
        return (value - prev_v) / (now - prev_t)


class _MetricFamily:
    """Common machinery: name/help/label validation + the child table."""

    kind = "untyped"
    _child_cls = _Child

    def __init__(self, name: str, help: str = "",  # noqa: A002
                 labelnames: Sequence[str] = ()):
        if not _METRIC_NAME_RE.match(name):
            raise MXNetError("invalid metric name %r" % name)
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln) or ln.startswith("__"):
                raise MXNetError("invalid label name %r on metric %r"
                                 % (ln, name))
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def labels(self, **labelkv) -> _Child:
        """The child bound to these label values (created on first use)."""
        if set(labelkv) != set(self.labelnames):
            raise MXNetError(
                "metric %r takes labels %r, got %r"
                % (self.name, list(self.labelnames), sorted(labelkv)))
        key = tuple(str(labelkv[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_cls(self, key)
                self._children[key] = child
            return child

    def _default_child(self) -> _Child:
        """The no-label child (only valid when labelnames is empty)."""
        if self.labelnames:
            raise MXNetError(
                "metric %r has labels %r; bind them with .labels()"
                % (self.name, list(self.labelnames)))
        return self.labels()

    def samples(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            children = list(self._children.items())
        return [(lv, child.get()) for lv, child in sorted(children)]

    def clear(self):
        """Zero every child's samples IN PLACE: bound children cached at
        call sites (module-level bindings in engine.py etc.) must stay
        valid across a registry reset."""
        with self._lock:
            for child in self._children.values():
                child._zero()


class Counter(_MetricFamily):
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    def get(self) -> float:
        return self._default_child().get()


class Gauge(_MetricFamily):
    """A value that can go up and down (queue depth, busy workers)."""

    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float):
        self._default_child().set(value)

    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default_child().dec(amount)

    def get(self) -> float:
        return self._default_child().get()


class Histogram(_MetricFamily):
    """Distribution over fixed log-scale buckets (latencies, sizes)."""

    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name, help="", labelnames=(),  # noqa: A002
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames)
        bounds = tuple(float(b) for b in
                       (DEFAULT_TIME_BUCKETS if buckets is None else buckets))
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise MXNetError(
                "histogram %r: bucket bounds must be sorted and unique"
                % name)
        self.buckets = bounds

    def observe(self, value: float):
        self._default_child().observe(value)

    def get(self) -> Dict[str, object]:
        return self._default_child().get()

    def quantile(self, q: float) -> float:
        return self._default_child().quantile(q)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricRegistry:
    """Thread-safe name -> metric family table with get-or-create
    semantics (modules and tests referring to the same name share one
    instrument, like the reference's per-name aggregate rows)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _MetricFamily] = {}

    def _get_or_create(self, cls, name, help, labelnames,  # noqa: A002
                       **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise MXNetError(
                        "metric %r already registered as %s, not %s"
                        % (name, m.kind, cls.kind))
                if tuple(labelnames) != m.labelnames:
                    raise MXNetError(
                        "metric %r already registered with labels %r"
                        % (name, list(m.labelnames)))
                return m
            m = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:  # noqa: A002
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:  # noqa: A002
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),  # noqa: A002
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_MetricFamily]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[_MetricFamily]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def reset(self):
        """Drop every recorded sample but keep the registered families
        (instrument objects cached at module scope stay valid)."""
        for m in self.collect():
            m.clear()
