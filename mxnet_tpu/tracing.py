"""Causal tracing: trace contexts, flow events, and the flight recorder.

The telemetry registry answers "what is the process doing right now" with
aggregates; profiler.py answers "where did this window of time go" with
isolated spans.  Neither shows *causality* — which push produced which
execution on which worker thread, which Var dependency serialized two
ops, which worker's KVStore push a server handler span belongs to.  This
module is that layer, in the Dapper mold, unified with the profiler's
Chrome-trace event stream:

- **Trace contexts.**  A span carries ``(trace_id, span_id)``; a
  thread-local stack links nested spans parent→child, and the engine and
  KVStore carry contexts across threads and processes explicitly.  Ids
  embed the pid (``"<pid-hex>.<seq-hex>"``) so they stay unique after a
  multi-process merge with no remapping.
- **Flow events.**  Engine pushes emit Chrome-trace flow events
  (``ph: s/t/f`` sharing an ``id``) linking the pushing thread's
  ``Engine::Push`` span to the worker's execution span and its
  completion; op spans are annotated with the Var names they waited on,
  so the dependency graph is visible in Perfetto.
- **Wire propagation.**  ``kvstore_server.send_msg(..., trace_ctx=...)``
  carries a compact ``{"t": trace_id, "s": span_id}`` context in the
  frame header; server handler spans adopt it, and
  ``tools/merge_traces.py`` merges per-process trace files into one
  clock-aligned trace keyed by rank.
- **Flight recorder.**  A fixed-size ring of the last N span records that
  stays warm even with the profiler stopped, dumped to JSON on
  ``MXNetError``, an engine worker crash, or ``SIGUSR2`` — post-mortem
  context for dist flakes.

Cost model (same discipline as telemetry): every built-in site is gated
by a single attribute check (``tracing.enabled`` / ``flight.enabled``) on
the disabled path.  Tracing is off by default (``MXNET_TRACING=1`` turns
it on; events are collected while the profiler runs).  The flight
recorder defaults ON because its steady-state cost is one ring append per
*recorded* span — and nothing records spans unless the profiler or
tracing is active, except the recorder's own crash markers.

Env knobs (see docs/observability.md "Tracing"): ``MXNET_TRACING``,
``MXNET_TRACE_DIR``, ``MXNET_FLIGHT_RECORDER``,
``MXNET_FLIGHT_RECORDER_SIZE``, ``MXNET_FLIGHT_RECORDER_PATH``,
``MXNET_FLIGHT_RECORDER_DEBOUNCE_SEC``, ``MXNET_PROFILER_MAX_EVENTS``.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import tempfile
import threading
import time
from typing import NamedTuple, Optional

from . import base as _base
from . import profiler as _profiler
from . import telemetry as _telemetry
from .base import get_env

__all__ = ["enabled", "enable", "disable", "span", "server_span",
           "current", "engine_push", "flight", "FlightRecorder",
           "dump_process_trace"]

#: single-attribute gate read by every built-in instrumentation site
enabled = False


def enable():
    global enabled
    enabled = True


def disable():
    global enabled
    enabled = False


_FLIGHT_DUMPS = _telemetry.counter(
    "flight_recorder_dumps_total",
    "Flight-recorder ring dumps, by trigger", ("reason",))


# ---------------------------------------------------------------------------
# ids and thread-local context
# ---------------------------------------------------------------------------
_id_lock = threading.Lock()
_id_n = 0


def _new_id() -> str:
    """Process-unique id: ``"<pid-hex>.<seq-hex>"``.

    Baking in the pid keeps flow/span ids collision-free across the
    processes of a dist run, so merge_traces.py never has to remap ids —
    a worker's flow-start and the server's flow-end keep matching."""
    global _id_n
    with _id_lock:
        _id_n += 1
        n = _id_n
    return "%x.%x" % (os.getpid() & 0xFFFFFFFF, n)


class SpanCtx(NamedTuple):
    trace_id: str
    span_id: str


_tls = threading.local()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> Optional[SpanCtx]:
    """The innermost active span context on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def _tid():
    return threading.get_ident() % 100000


def _emit_flow(ph, flow_id, name, cat, ts=None, bind_enclosing=False):
    """Append one Chrome flow event (``s``/``t``/``f``).

    Flow events bind by (cat, name, id), so all events of one flow use
    identical name/cat.  ``bind_enclosing`` sets ``"bp": "e"`` — the
    flow-end attaches to the slice enclosing its timestamp."""
    if not _profiler.is_running():
        return
    ev = {"name": name, "cat": cat, "ph": ph, "id": flow_id,
          "ts": _profiler._now_us() if ts is None else ts,
          "pid": os.getpid(), "tid": _tid()}
    if bind_enclosing:
        ev["bp"] = "e"
    _profiler._append_event(ev)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class _TraceSpan:
    """A traced span: records an X event with trace/span/parent ids in
    ``args`` and maintains the thread-local context stack.

    ``parent`` may be another span/SpanCtx, a wire context dict
    (``{"t": trace_id, "s": span_id}``), or None (inherit from the
    thread's current context, else start a new trace)."""

    __slots__ = ("name", "cat", "extra", "trace_id", "span_id",
                 "parent_id", "_begin")

    def __init__(self, name, cat="trace", parent=None, args=None):
        self.name = name
        self.cat = cat
        self.extra = args
        if parent is None:
            parent = current()
        if isinstance(parent, dict):          # wire trace context
            self.trace_id = parent.get("t") or _new_id()
            self.parent_id = parent.get("s")
        elif parent is not None:              # SpanCtx or another span
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = _new_id()
            self.parent_id = None
        self.span_id = _new_id()

    def __enter__(self):
        self._begin = _profiler._now_us()
        _stack().append(SpanCtx(self.trace_id, self.span_id))
        return self

    def __exit__(self, *exc):
        st = _stack()
        if st:
            st.pop()
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            args["parent_id"] = self.parent_id
        if self.extra:
            args.update(self.extra)
        _profiler.record_span(self.name, self._begin, _profiler._now_us(),
                              self.cat, args=args)
        return False

    def flow_out(self, name="kvstore_flow"):
        """Start a flow from this span; returns the wire trace context
        to embed in an outgoing message."""
        _emit_flow("s", self.span_id, name, self.cat, ts=self._begin)
        return {"t": self.trace_id, "s": self.span_id}

    def wire_ctx(self):
        return {"t": self.trace_id, "s": self.span_id}


def span(name, cat="trace", parent=None, args=None) -> _TraceSpan:
    """Context manager for a traced span (see :class:`_TraceSpan`)."""
    return _TraceSpan(name, cat, parent=parent, args=args)


class _ServerSpan(_TraceSpan):
    """Handler-side span that adopts an incoming wire trace context and
    terminates the sender's flow inside itself."""

    __slots__ = ("_in_flow",)

    def __init__(self, name, tc, cat="kvstore"):
        super().__init__(name, cat, parent=tc if tc else None)
        self._in_flow = tc.get("s") if tc else None

    def __enter__(self):
        super().__enter__()
        if self._in_flow:
            # bp=e binds the flow-end to this (enclosing) handler slice
            _emit_flow("f", self._in_flow, "kvstore_flow", self.cat,
                       bind_enclosing=True)
        return self


def server_span(name, tc, cat="kvstore") -> _ServerSpan:
    """Span adopting a wire trace context ``{"t":..., "s":...}`` (or
    None); emits the matching flow-end for the sender's flow-start."""
    return _ServerSpan(name, tc, cat=cat)


# ---------------------------------------------------------------------------
# engine causality: push → execute → complete flows
# ---------------------------------------------------------------------------
def _var_name(v):
    n = getattr(v, "name", None)
    return n if n else "var@%x" % (id(v) & 0xFFFFFF)


class _EngineFlow:
    """One engine op's causal record, created on the pushing thread and
    completed on the worker thread.  Emits:

    - ``Engine::Push`` span + flow-start (``s``) on the pushing thread,
    - flow-step (``t``) + the op's execution span (annotated with the Var
      names it waited on and its trace/span/parent ids) on the worker,
    - ``Engine::OnComplete`` span + flow-end (``f``) at completion.
    """

    __slots__ = ("name", "trace_id", "parent_id", "flow_id", "span_id",
                 "const_names", "mutable_names", "_t_push", "_t_exec")

    def pushed(self):
        """Record the push span + flow-start (pushing thread)."""
        end = _profiler._now_us()
        _emit_flow("s", self.flow_id, "engine_flow", "engine",
                   ts=self._t_push)
        _profiler.record_span(
            "Engine::Push", self._t_push, end, "engine",
            args={"op": self.name, "trace_id": self.trace_id,
                  "flow_id": self.flow_id})

    def exec_begin(self):
        """Worker thread enters the op: flow-step + context push."""
        self.span_id = _new_id()
        self._t_exec = _profiler._now_us()
        _emit_flow("t", self.flow_id, "engine_flow", "engine",
                   ts=self._t_exec)
        _stack().append(SpanCtx(self.trace_id, self.span_id))

    def exec_end(self, error=None):
        """Worker thread leaves the op: record the execution span."""
        st = _stack()
        if st:
            st.pop()
        end = _profiler._now_us()
        args = {"trace_id": self.trace_id, "span_id": self.span_id,
                "flow_id": self.flow_id,
                "const_vars": self.const_names,
                "mutable_vars": self.mutable_names}
        if self.parent_id:
            args["parent_id"] = self.parent_id
        if error is not None:
            args["error"] = "%s: %s" % (type(error).__name__, error)
        _profiler.record_span(self.name, self._t_exec, end, "engine_op",
                              args=args)

    def completed(self):
        """Dependency release: tiny span + flow-end bound to it."""
        b = _profiler._now_us()
        _emit_flow("f", self.flow_id, "engine_flow", "engine", ts=b,
                   bind_enclosing=True)
        _profiler.record_span("Engine::OnComplete", b, _profiler._now_us(),
                              "engine",
                              args={"op": self.name, "flow_id": self.flow_id})


def engine_push(name, const_vars=(), mutable_vars=()) -> _EngineFlow:
    """Begin a push→execute→complete flow (call on the pushing thread).

    Inherits the pushing thread's current span context, so ops pushed
    from inside a traced span (or from inside another engine op's fn)
    join that trace with a parent link."""
    cur = current()
    fl = _EngineFlow()
    fl.name = name or "engine_op"
    fl.trace_id = cur.trace_id if cur is not None else _new_id()
    fl.parent_id = cur.span_id if cur is not None else None
    fl.flow_id = _new_id()
    fl.span_id = None
    fl.const_names = [_var_name(v) for v in const_vars]
    fl.mutable_names = [_var_name(v) for v in mutable_vars]
    fl._t_push = _profiler._now_us()
    fl._t_exec = 0.0
    return fl


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Fixed-size ring of the last N span records, always warm.

    ``profiler.record_span`` feeds it regardless of profiler state (one
    deque append per recorded span; ``maxlen`` handles eviction in C).
    Dumped to JSON on MXNetError construction (debounced — the test
    suite raises MXNetError intentionally all over), on an engine worker
    crash or SIGUSR2 (both forced), or manually via :meth:`dump`."""

    def __init__(self):
        self.enabled = get_env("MXNET_FLIGHT_RECORDER", True, bool)
        size = max(16, get_env("MXNET_FLIGHT_RECORDER_SIZE", 1024, int))
        self._ring = collections.deque(maxlen=size)
        self._dump_lock = threading.Lock()
        self._last_error_dump = 0.0
        self.error_debounce = get_env(
            "MXNET_FLIGHT_RECORDER_DEBOUNCE_SEC", 1.0, float)

    # -- recording ---------------------------------------------------------
    def record(self, name, category, begin_us, end_us, args=None):
        self._ring.append((begin_us, end_us - begin_us, name, category,
                           _tid(), args))

    def clear(self):
        self._ring.clear()
        self._last_error_dump = 0.0

    def __len__(self):
        return len(self._ring)

    # -- dumping -----------------------------------------------------------
    def path(self):
        """Dump path, resolved at dump time so tests can redirect it."""
        return (os.environ.get("MXNET_FLIGHT_RECORDER_PATH")
                or os.path.join(tempfile.gettempdir(),
                                "mxnet_flight_recorder_%d.json" % os.getpid()))

    def dump(self, reason="manual"):
        """Write the ring to JSON atomically; returns the path (or None —
        a post-mortem dump must never raise into the failing path)."""
        _FLIGHT_DUMPS.labels(reason=reason).inc()
        with self._dump_lock:
            try:
                events = [{"ts_us": ts, "dur_us": dur, "name": name,
                           "cat": cat, "tid": tid, "args": args}
                          for (ts, dur, name, cat, tid, args)
                          in list(self._ring)]
                doc = {"reason": reason,
                       "unix_time": time.time(),
                       "pid": os.getpid(),
                       "rank": os.environ.get("DMLC_WORKER_ID", "0"),
                       "role": os.environ.get("DMLC_ROLE", "worker"),
                       "t0_unix_us": time.time() * 1e6 - _profiler._now_us(),
                       "events": events}
                # post-mortem program context: which cached XLA programs
                # were live (cost + the env flags that built them), plus
                # the atlas per-scope tables when available.  The programs
                # block does not depend on atlas being enabled.
                try:
                    from . import health as _health
                    progs = {n: pc.as_dict()
                             for n, pc in _health.programs().items()}
                    if progs:
                        doc["programs"] = progs
                except Exception:
                    pass
                try:
                    from . import atlas as _atlas
                    at = _atlas.snapshot(top_k=10)
                    if at:
                        doc["atlas"] = at
                except Exception:
                    pass
                # trailing metric history: the minutes *leading up to*
                # the trip, not just the spans after it (empty until the
                # time-series sampler has run at least once).
                try:
                    from .telemetry import timeseries as _ts
                    win = get_env("MXNET_FLIGHT_TS_WINDOW", 120.0, float)
                    tsdoc = _ts.trailing(window_seconds=win)
                    if tsdoc.get("series"):
                        doc["timeseries"] = tsdoc
                except Exception:
                    pass
                # fleet context: when this process runs the fleet
                # collector, its dump carries the merged target table,
                # derived aggregates and alert state (per-rank evidence
                # lives in the offending rank's own dump).
                try:
                    from .telemetry import fleet as _fleet
                    if _fleet.running():
                        blk = _fleet.flight_block()
                        if blk:
                            doc["fleet"] = blk
                except Exception:
                    pass
                # memory forensics: the owner-tagged ledger, the leak
                # suspects table and the last registered program's
                # footprint (the oom_risk / reason=oom evidence).
                try:
                    from . import memwatch as _memwatch
                    if _memwatch.enabled:
                        doc["memwatch"] = _memwatch.forensics()
                except Exception:
                    pass
                path = self.path()
                tmp = "%s.tmp.%d" % (path, os.getpid())
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=str)
                os.replace(tmp, path)
                return path
            except Exception:
                return None

    # -- triggers ----------------------------------------------------------
    def on_engine_crash(self, name, exc, wait_on=None):
        """Forced dump when an engine op's fn raised (the crash origin,
        not downstream ops poisoned by dependency propagation)."""
        if not self.enabled:
            return
        args = {"error": "%s: %s" % (type(exc).__name__, exc)}
        if wait_on:
            args["wait_on"] = list(wait_on)
        self._ring.append((_profiler._now_us(), 0.0,
                           "CRASH " + (name or "engine_op"), "crash",
                           _tid(), args))
        self.dump("engine_crash")

    def _on_mxnet_error(self, exc):
        """base.MXNetError construction hook (debounced)."""
        if not self.enabled:
            return
        now = time.monotonic()
        if now - self._last_error_dump < self.error_debounce:
            return
        self._last_error_dump = now
        self._ring.append((_profiler._now_us(), 0.0, "MXNetError", "error",
                           _tid(), {"error": str(exc)}))
        self.dump("mxnet_error")


flight = FlightRecorder()


def _install_sigusr2():
    """kill -USR2 <pid> dumps the ring of a live process (main thread
    only — signal.signal raises elsewhere, e.g. under some test runners)."""
    if not hasattr(signal, "SIGUSR2"):
        return
    try:
        if threading.current_thread() is not threading.main_thread():
            return
        prev = signal.getsignal(signal.SIGUSR2)

        def _handler(signum, frame):
            flight.dump("sigusr2")
            if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
                prev(signum, frame)

        signal.signal(signal.SIGUSR2, _handler)
    except (ValueError, OSError):
        pass


# ---------------------------------------------------------------------------
# per-process trace files for dist runs
# ---------------------------------------------------------------------------
def dump_process_trace(role=None, directory=None):
    """Dump this process's profiler events to ``$MXNET_TRACE_DIR`` under a
    rank/role-keyed name (``trace_server.json`` / ``trace_worker<r>.json``)
    for ``tools/merge_traces.py``.  No-op when no directory is configured."""
    directory = directory or os.environ.get("MXNET_TRACE_DIR")
    if not directory:
        return None
    role = role or os.environ.get("DMLC_ROLE") or "worker"
    if role == "server":
        fname = "trace_server.json"
    else:
        fname = "trace_%s%s.json" % (
            role, os.environ.get("DMLC_WORKER_ID", "0") or "0")
    os.makedirs(directory, exist_ok=True)
    return _profiler.dump(filename=os.path.join(directory, fname))


# ---------------------------------------------------------------------------
# wiring
# ---------------------------------------------------------------------------
_profiler._flight = flight
_base._ERROR_HOOK = flight._on_mxnet_error
_install_sigusr2()

if get_env("MXNET_TRACING", False, bool):
    enable()
