#!/usr/bin/env python
"""Perf regression sentinel: canonical bench trajectory + tolerance gate.

The repo's perf record is heterogeneous — ``BENCH_rNN.json`` driver
wrappers, ``MULTICHIP_rNN.json`` mesh rounds, and (since the run ledger)
``bench_result`` events in ``runlog`` JSONL files — and it was compared
by hand, if at all.  This tool is the mechanical comparison, in the
MLPerf round-over-round mold:

1. **normalize**: every input shape collapses into one canonical round
   document ``{"round", "source", "kind", "metrics": {name: value},
   "context": {...}}`` with stable metric names (resnet50_img_per_sec,
   lstm_tokens_per_sec, multichip_scaling_efficiency, ...).
2. **compare**: candidate vs committed baseline, one tolerance band per
   metric (direction + relative tolerance + absolute slack — spread and
   overhead metrics get absolute points, throughput gets percent).
   Improvements always pass; regressions beyond the band FAIL, beyond
   half the band WARN.  Output is a ranked markdown verdict table
   (worst first) or JSON; exit is nonzero on any FAIL.
3. **--update-baseline**: promote the candidate to
   ``bench_history/baseline.json`` after a reviewed run.

``bench.py`` appends each round to the run ledger and invokes
:func:`compare` automatically (``BENCH_SENTINEL=0`` to opt out), so a
regression is caught the moment the bench runs — not at the next human
re-read of the trajectory.

Stdlib-only on purpose: the gate must run anywhere (CI shard, dev box,
pre-push hook) without importing the framework or jax.

    python tools/sentinel.py --candidate BENCH_r05.json
    python tools/sentinel.py --candidate runs/ledger.jsonl --format md
    python tools/sentinel.py --normalize BENCH_r0*.json -o bench_history/
    python tools/sentinel.py --candidate new.json --update-baseline
    python tools/sentinel.py --smoke
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "bench_history", "baseline.json")

# ---------------------------------------------------------------------------
# tolerance bands: metric -> (direction, rel_tol, abs_slack)
#
# direction says which way is GOOD; a move the good way always passes.
# The band the bad way is max(rel_tol * |baseline|, abs_slack): percent
# for throughput-like metrics, absolute points for spreads/overheads
# (2% -> 5% spread is a real regression a relative band would miss when
# the baseline is small, and a 50% relative band would miss when it is
# large).  A candidate breaching the full band FAILs, half of it WARNs.
# ---------------------------------------------------------------------------
TOLERANCES: Dict[str, Tuple[str, float, float]] = {
    "resnet50_img_per_sec":         ("higher", 0.10, 0.0),
    "resnet50_mfu_pct":             ("higher", 0.10, 0.0),
    "resnet50_step_spread_pct":     ("lower",  0.00, 3.0),
    "lstm_tokens_per_sec":          ("higher", 0.10, 0.0),
    "lstm_mfu_pct":                 ("higher", 0.10, 0.0),
    "lstm_step_spread_pct":         ("lower",  0.00, 3.0),
    "multichip_img_per_sec":        ("higher", 0.10, 0.0),
    "multichip_scaling_efficiency": ("higher", 0.15, 0.0),
    "serving_p99_ms":               ("lower",  0.20, 0.0),
    "serving_throughput_rps":       ("higher", 0.10, 0.0),
    # SLO gateway (ISSUE 14): realtime tail at the >10x-capacity
    # open-loop point.  Absolute slack because the CPU box's batch
    # timing wobbles tens of ms run to run; a realtime tail that grows
    # past band means admission control stopped protecting the class.
    "serving_p99_ms_realtime":      ("lower",  0.30, 25.0),
    # shed rate at 12x offered load: HIGHER is healthy (overload is
    # absorbed as explicit 429s).  A collapse toward 0 under the same
    # overload means shedding broke and the tail is eating it.
    "serving_shed_rate_overload":   ("higher", 0.00, 0.25),
    "post_warmup_compiles":         ("lower",  0.00, 0.0),
    "atlas_coverage_pct":           ("higher", 0.00, 5.0),
    "monitor_overhead_pct":         ("lower",  0.00, 1.0),
    "sampler_overhead_pct":         ("lower",  0.00, 1.0),
    # donation-safe async checkpoints (ISSUE 13): amortized per-step cost
    # of the live TrainCheckpointer; the acceptance bar is <3%
    "checkpoint_overhead_pct":      ("lower",  0.00, 3.0),
    # cold-start currency (program_cache.py).  Lower is better; a warm
    # deploy (prefilled cache dir) improves 5x+ and always passes.  The
    # bands are generous because the COLD path is compile-time noise on
    # shared CPU — only a 1.5x-plus-slack blowup is a real regression
    # (an accidental cache bypass shows up as exactly that).
    "step_first_compile_seconds":   ("lower",  0.50, 3.0),
    "serving_warmup_seconds":       ("lower",  0.50, 2.0),
    # device-memory observability (ISSUE 16): the resnet50 round's
    # per-device peak — LOWER is good; a step that suddenly holds more
    # HBM regressed even if it got faster.  Generous absolute slack
    # because the CPU census-fallback peak moves with unrelated process
    # residents.
    "resnet50_peak_bytes_in_use":   ("lower",  0.25, float(8 << 20)),
    # census + ledger hooks must stay at noise level, same bar as the
    # monitor/sampler
    "memwatch_overhead_pct":        ("lower",  0.00, 1.0),
    # bf16 mixed precision (ISSUE 19).  Throughput on CPU is an
    # emulation canary (XLA upcasts per op) that wobbles ±50% with host
    # load at the small CPU iteration count, so the band only catches a
    # collapse; the load-bearing rows are the footprint ratios — params
    # must stay at ~half of fp32 and the peak must not creep back
    # toward the fp32 peak.  Re-band on a real chip.
    "resnet50_bf16_img_per_sec":    ("higher", 0.50, 0.0),
    "resnet50_bf16_peak_bytes_in_use": ("lower", 0.25, float(8 << 20)),
    # ratios are bounded [0, ~1]: absolute slack, no relative band
    "bf16_params_ratio":            ("lower",  0.00, 0.05),
    "bf16_params_activations_ratio": ("lower", 0.00, 0.08),
    # transformer LM workload (ISSUE 20).  CPU throughput on the small
    # iteration count wobbles with host load (same story as bf16), so
    # the bands catch a collapse, not a wobble; re-band on a real chip.
    # The zero-tolerance compile row and the atlas floor are the
    # load-bearing gates — they are also what --smoke asserts.
    "transformer_tokens_per_sec":   ("higher", 0.35, 0.0),
    "transformer_mfu_pct":          ("higher", 0.35, 0.0),
    "transformer_step_spread_pct":  ("lower",  0.00, 8.0),
    "transformer_post_warmup_compiles": ("lower", 0.00, 0.0),
    "transformer_atlas_coverage_pct": ("higher", 0.00, 5.0),
    "transformer_peak_bytes_in_use": ("lower", 0.30, float(8 << 20)),
}
#: band for metrics not in the table: 15% relative, either direction bad
#: is unknowable, so assume higher-is-better (throughput-style default).
DEFAULT_BAND = ("higher", 0.15, 0.0)


def _num(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f and abs(f) != float("inf") else None


# ---------------------------------------------------------------------------
# normalizers: every known perf-record shape -> canonical round doc
# ---------------------------------------------------------------------------
def _round_of(path: str) -> Optional[str]:
    m = re.search(r"r(\d+)", os.path.basename(path or ""))
    return "r%02d" % int(m.group(1)) if m else None


def _norm_bench_parsed(parsed: dict, source: str) -> dict:
    """The ``parsed`` block of a BENCH_rNN wrapper / bench.py stdout."""
    metrics: Dict[str, float] = {}
    ctx: Dict[str, object] = {}

    def put(name, v):
        v = _num(v)
        if v is not None:
            metrics[name] = v

    put("resnet50_img_per_sec", parsed.get("value"))
    put("resnet50_mfu_pct", parsed.get("mfu_pct"))
    put("resnet50_step_spread_pct", parsed.get("step_spread_pct"))
    put("step_first_compile_seconds",
        parsed.get("step_first_compile_seconds"))
    put("checkpoint_overhead_pct", parsed.get("checkpoint_overhead_pct"))
    lstm = parsed.get("lstm")
    if isinstance(lstm, dict) and "error" not in lstm:
        put("lstm_tokens_per_sec", lstm.get("value"))
        put("lstm_mfu_pct", lstm.get("mfu_pct"))
        put("lstm_step_spread_pct", lstm.get("step_spread_pct"))
    health = parsed.get("health")
    if isinstance(health, dict):
        put("monitor_overhead_pct", health.get("monitor_overhead_pct"))
        put("sampler_overhead_pct", health.get("sampler_overhead_pct"))
    memory = parsed.get("memory")
    if isinstance(memory, dict) and "error" not in memory:
        put("resnet50_peak_bytes_in_use", memory.get("peak_bytes_in_use"))
        put("memwatch_overhead_pct", memory.get("memwatch_overhead_pct"))
    atlas = parsed.get("atlas")
    if isinstance(atlas, dict) and "error" not in atlas:
        covs = [_num(a.get("coverage_pct")) for a in atlas.values()
                if isinstance(a, dict)]
        covs = [c for c in covs if c is not None]
        if covs:
            # the gate watches the WORST program: attribution rotting in
            # one program is invisible to a mean over many healthy ones
            metrics["atlas_coverage_pct"] = min(covs)
    for k in ("window_suspect", "dtype", "batch", "unit"):
        if k in parsed:
            ctx[k] = parsed[k]
    # r01-style records predate the window validation: no scaling ratio
    # means the number never proved itself — flagged, never baselined
    if "window_scaling_ratio" not in parsed:
        ctx["unvalidated"] = True
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "bench", "metrics": metrics, "context": ctx}


def _norm_bench_bf16(doc: dict, source: str) -> dict:
    """bench.py --bf16 record (ISSUE 19).  The throughput row keeps the
    model-qualified metric name the bench emitted (``resnet50_bf16_*``);
    the footprint ratios are model-agnostic bands — on any model, bf16
    params at more than ~half of fp32 means the cast policy broke."""
    metrics: Dict[str, float] = {}

    def put(name, v):
        v = _num(v)
        if v is not None:
            metrics[name] = v

    name = str(doc.get("metric") or "bf16_img_per_sec")
    put(name, doc.get("value"))
    put(name.replace("_img_per_sec", "_peak_bytes_in_use"),
        doc.get("peak_bytes_in_use"))
    put("bf16_params_ratio", doc.get("params_ratio"))
    put("bf16_params_activations_ratio",
        doc.get("params_activations_ratio"))
    ctx = {k: doc[k] for k in ("model", "batch", "platform", "unit",
                               "throughput_chip_pending", "loss_delta",
                               "matched_convergence", "footprint_halved",
                               "ok") if k in doc}
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "bench_bf16", "metrics": metrics, "context": ctx}


def _norm_bench_transformer(doc: dict, source: str) -> dict:
    """bench.py --transformer record (ISSUE 20): decoder-LM tokens/s +
    MFU, the zero-tolerance post-warmup compile count, the worst-program
    atlas coverage and the per-device peak.  Metric names are
    transformer-qualified so merging into the baseline never collides
    with the resnet/serving rows of the same name."""
    metrics: Dict[str, float] = {}

    def put(name, v):
        v = _num(v)
        if v is not None:
            metrics[name] = v

    put("transformer_tokens_per_sec", doc.get("value"))
    put("transformer_mfu_pct", doc.get("mfu_pct"))
    put("transformer_step_spread_pct", doc.get("step_spread_pct"))
    put("transformer_post_warmup_compiles",
        doc.get("post_warmup_compiles"))
    put("transformer_atlas_coverage_pct",
        doc.get("atlas_coverage_min_pct"))
    put("transformer_peak_bytes_in_use", doc.get("peak_bytes_in_use"))
    ctx = {k: doc[k] for k in ("config", "batch", "seq_len", "dtype",
                               "platform", "n_params", "unit",
                               "attention_dispatch", "window_suspect",
                               "last_loss", "ok") if k in doc}
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "bench_transformer", "metrics": metrics,
            "context": ctx}


def _norm_multichip(doc: dict, source: str) -> dict:
    metrics: Dict[str, float] = {}
    v = _num(doc.get("value") if doc.get("value") is not None
             else doc.get("img_per_sec"))
    if v is not None:
        metrics["multichip_img_per_sec"] = v
    e = _num(doc.get("scaling_efficiency"))
    if e is not None:
        metrics["multichip_scaling_efficiency"] = e
    ctx = {k: doc[k] for k in ("platform", "n_devices", "model", "batch",
                               "window_suspect", "ok", "skipped")
           if k in doc}
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "multichip", "metrics": metrics, "context": ctx}


def _norm_serving(doc: dict, source: str) -> dict:
    """tools/bench_serving.py result or a ledger serving payload."""
    metrics: Dict[str, float] = {}
    for src, dst in (("p99_ms", "serving_p99_ms"),
                     ("latency_p99_ms", "serving_p99_ms"),
                     ("throughput_rps", "serving_throughput_rps"),
                     ("post_warmup_compiles", "post_warmup_compiles"),
                     ("warmup_seconds", "serving_warmup_seconds")):
        v = _num(doc.get(src))
        if v is not None and dst not in metrics:
            metrics[dst] = v
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "serving", "metrics": metrics, "context": {}}


def _norm_serving_gateway(doc: dict, source: str) -> dict:
    """tools/bench_serving.py output with the SLO saturation sweep: the
    gated metrics come from the worst (last) sweep point."""
    metrics: Dict[str, float] = {}
    ctx: Dict[str, object] = {}
    closed = doc.get("closed") or {}
    v = _num(closed.get("throughput_rps"))
    if v is not None:
        metrics["serving_throughput_rps"] = v
    v = _num(doc.get("warmup_seconds"))
    if v is not None:
        metrics["serving_warmup_seconds"] = v
    v = _num(doc.get("post_warmup_compiles"))
    if v is not None:
        metrics["post_warmup_compiles"] = v
    sweep = doc.get("sweep") or []
    if sweep:
        sat = sweep[-1]
        v = _num(sat.get("shed_rate"))
        if v is not None:
            metrics["serving_shed_rate_overload"] = v
        rt = (sat.get("classes") or {}).get("realtime") or {}
        v = _num(rt.get("p99_ms"))
        if v is not None:
            metrics["serving_p99_ms_realtime"] = v
        ctx["overload_offered_rps"] = sat.get("offered_rps")
        ctx["capacity_multiple"] = sat.get("capacity_multiple")
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "serving_gateway", "metrics": metrics, "context": ctx}


def _norm_ledger(path: str) -> dict:
    """A runlog JSONL: fold every bench_result / healthz event into one
    candidate round (the run's final state wins per metric)."""
    metrics: Dict[str, float] = {}
    ctx: Dict[str, object] = {}
    run_id = None
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line: JSONL readers skip, not die
            if not isinstance(rec, dict):
                continue
            run_id = rec.get("run_id", run_id)
            ev = rec.get("event")
            if ev == "bench_result":
                res = rec.get("result")
                if isinstance(res, dict):
                    sub = normalize(res, rec.get("source_name", path))
                    metrics.update(sub["metrics"])
                    ctx.update(sub["context"])
            elif ev == "healthz":
                v = _num(rec.get("post_warmup_compiles"))
                if v is not None:
                    metrics["post_warmup_compiles"] = v
            elif ev == "serving_warmup":
                v = _num(rec.get("seconds"))
                if v is not None:
                    metrics["serving_warmup_seconds"] = v
            elif ev == "run_start":
                env = rec.get("env")
                if isinstance(env, dict):
                    ctx.setdefault("step_env", {
                        k: env[k] for k in
                        ("MXNET_TPU_FUSED_STEP", "MXNET_TPU_MESH_STEP")
                        if k in env})
    if run_id:
        ctx["run_id"] = run_id
    return {"round": _round_of(path), "source": os.path.basename(path),
            "kind": "ledger", "metrics": metrics, "context": ctx}


def normalize(doc, source: str = "<inline>") -> dict:
    """Dispatch on shape: canonical round / driver wrapper / bench parsed
    / multichip / serving dicts all collapse to the canonical form."""
    if isinstance(doc, str):
        if doc.endswith(".jsonl"):
            return _norm_ledger(doc)
        with open(doc, "r", encoding="utf-8") as f:
            return normalize(json.load(f), doc)
    if not isinstance(doc, dict):
        raise ValueError("cannot normalize %r from %s" % (type(doc), source))
    if isinstance(doc.get("metrics"), dict):            # already canonical
        out = dict(doc)
        out.setdefault("source", os.path.basename(source))
        return out
    if isinstance(doc.get("parsed"), dict):             # driver wrapper
        return _norm_bench_parsed(doc["parsed"], source)
    if "scaling_efficiency" in doc or "n_devices" in doc:
        return _norm_multichip(doc, source)
    if "throughput_chip_pending" in doc:                # bench.py --bf16
        return _norm_bench_bf16(doc, source)
    if "flops_per_token" in doc:                 # bench.py --transformer
        return _norm_bench_transformer(doc, source)
    if doc.get("bench") == "serving" or "sweep" in doc:
        return _norm_serving_gateway(doc, source)
    if "p99_ms" in doc or "latency_p99_ms" in doc or \
            "throughput_rps" in doc:
        return _norm_serving(doc, source)
    if "value" in doc or "mfu_pct" in doc:              # bare parsed block
        return _norm_bench_parsed(doc, source)
    # nothing recognizable: canonical-but-empty keeps the pipeline total
    return {"round": _round_of(source), "source": os.path.basename(source),
            "kind": "unknown", "metrics": {}, "context": {}}


def merge_rounds(rounds: List[dict]) -> dict:
    """Several normalized docs (bench + multichip + serving of one round)
    into one: later docs win metric collisions."""
    out = {"round": None, "source": [], "kind": "merged",
           "metrics": {}, "context": {}}
    for r in rounds:
        out["round"] = r.get("round") or out["round"]
        out["source"].append(r.get("source"))
        out["metrics"].update(r.get("metrics") or {})
        out["context"].update(r.get("context") or {})
    return out


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------
def band_of(metric: str) -> Tuple[str, float, float]:
    return TOLERANCES.get(metric, DEFAULT_BAND)


def compare(baseline: dict, candidate: dict) -> List[dict]:
    """Verdict rows, ranked worst-first.  Both args are canonical round
    docs.  A metric present only in the candidate is informational
    (NEW); one that vanished is a WARN — silent metric loss is how
    regressions hide."""
    b_m = baseline.get("metrics") or {}
    c_m = candidate.get("metrics") or {}
    rows = []
    for name in sorted(set(b_m) | set(c_m)):
        b, c = _num(b_m.get(name)), _num(c_m.get(name))
        direction, rel, slack = band_of(name)
        band = max(rel * abs(b), slack) if b is not None else 0.0
        if b is None:
            rows.append({"metric": name, "baseline": None, "candidate": c,
                         "delta_pct": None, "band": band,
                         "verdict": "NEW", "excess": -1.0})
            continue
        if c is None:
            rows.append({"metric": name, "baseline": b, "candidate": None,
                         "delta_pct": None, "band": band,
                         "verdict": "MISSING", "excess": 0.5})
            continue
        delta = c - b
        delta_pct = (100.0 * delta / abs(b)) if b else None
        bad = -delta if direction == "higher" else delta
        if bad <= 0:
            verdict, excess = "PASS", -1.0
        elif band <= 0:
            verdict, excess = "FAIL", float("inf")  # zero-tolerance metric
        elif bad > band:
            verdict, excess = "FAIL", bad / band
        elif bad > 0.5 * band:
            verdict, excess = "WARN", bad / band
        else:
            verdict, excess = "PASS", bad / band
        rows.append({"metric": name, "baseline": b, "candidate": c,
                     "delta_pct": delta_pct, "band": band,
                     "verdict": verdict, "excess": excess})
    order = {"FAIL": 0, "WARN": 1, "MISSING": 2, "PASS": 3, "NEW": 4}
    rows.sort(key=lambda r: (order.get(r["verdict"], 9), -r["excess"],
                             r["metric"]))
    return rows


def verdict_exit(rows: List[dict]) -> int:
    return 1 if any(r["verdict"] == "FAIL" for r in rows) else 0


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and v == float("inf"):
        return "inf"
    return "%.4g" % v


def markdown_table(rows: List[dict], baseline: dict,
                   candidate: dict) -> str:
    def _name(doc, fallback):
        src = doc.get("source") or doc.get("round") or fallback
        if isinstance(src, (list, tuple)):
            src = "+".join(str(s) for s in src)
        return src

    lines = [
        "## sentinel verdict: %s vs baseline %s"
        % (_name(candidate, "candidate"), _name(baseline, "?")),
        "",
        "| metric | baseline | candidate | delta | band | verdict |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        direction, _, _ = band_of(r["metric"])
        arrow = "^" if direction == "higher" else "v"
        delta = ("%+.1f%%" % r["delta_pct"]
                 if r["delta_pct"] is not None else "-")
        lines.append("| %s (%s) | %s | %s | %s | %s | **%s** |" % (
            r["metric"], arrow, _fmt(r["baseline"]), _fmt(r["candidate"]),
            delta, _fmt(r["band"]), r["verdict"]))
    n_fail = sum(1 for r in rows if r["verdict"] == "FAIL")
    n_warn = sum(1 for r in rows if r["verdict"] == "WARN")
    lines += ["", "**%s** — %d FAIL, %d WARN, %d metrics compared"
              % ("REGRESSION" if n_fail else "OK", n_fail, n_warn,
                 len(rows))]
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# smoke: self-test the whole pipe on synthetic + committed data
# ---------------------------------------------------------------------------
def smoke() -> int:
    base = {"round": "rA", "source": "synthetic-base", "kind": "bench",
            "metrics": {"resnet50_img_per_sec": 2450.0,
                        "resnet50_mfu_pct": 30.6,
                        "resnet50_step_spread_pct": 0.7,
                        "lstm_tokens_per_sec": 460000.0},
            "context": {}}
    ok = True
    # identical runs must pass
    rows = compare(base, dict(base))
    ok &= verdict_exit(rows) == 0 and all(
        r["verdict"] == "PASS" for r in rows)
    # a ~20% throughput regression must FAIL, ranked first
    cand = json.loads(json.dumps(base))
    cand["metrics"]["resnet50_img_per_sec"] *= 0.8
    rows = compare(base, cand)
    ok &= verdict_exit(rows) == 1
    ok &= rows[0]["metric"] == "resnet50_img_per_sec" \
        and rows[0]["verdict"] == "FAIL"
    # a within-band wobble must not fail
    cand2 = json.loads(json.dumps(base))
    cand2["metrics"]["resnet50_img_per_sec"] *= 0.97
    ok &= verdict_exit(compare(base, cand2)) == 0
    # improvements always pass, even huge ones
    cand3 = json.loads(json.dumps(base))
    cand3["metrics"]["resnet50_img_per_sec"] *= 2.0
    cand3["metrics"]["resnet50_step_spread_pct"] = 0.0
    ok &= verdict_exit(compare(base, cand3)) == 0
    # the real committed record must normalize to non-empty metrics
    r05 = os.path.join(REPO, "BENCH_r05.json")
    if os.path.exists(r05):
        n = normalize(r05)
        ok &= bool(n["metrics"]) and \
            "resnet50_img_per_sec" in n["metrics"]
    if os.path.exists(DEFAULT_BASELINE):
        with open(DEFAULT_BASELINE) as f:
            bdoc = json.load(f)
        ok &= isinstance(bdoc.get("metrics"), dict) and bool(bdoc["metrics"])
        # two identical runs of the committed baseline must pass
        ok &= verdict_exit(compare(bdoc, bdoc)) == 0
    print(json.dumps({"probe": "sentinel", "ok": bool(ok)}))
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sentinel.py",
        description="perf regression gate over the canonical bench "
                    "trajectory")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="committed baseline round (canonical JSON)")
    ap.add_argument("--candidate", nargs="*", default=[],
                    help="candidate record(s): BENCH/MULTICHIP JSON, "
                         "runlog .jsonl, or canonical; several merge "
                         "into one round")
    ap.add_argument("--normalize", nargs="*", default=[],
                    help="normalize these files and write/print the "
                         "canonical docs instead of comparing")
    ap.add_argument("-o", "--out", default=None,
                    help="output dir (--normalize) or file")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the merged candidate over --baseline "
                         "after comparing")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test the normalize/compare pipeline")
    args = ap.parse_args(argv)

    if args.smoke:
        return smoke()

    if args.normalize:
        paths = [p for pat in args.normalize for p in
                 (sorted(glob.glob(pat)) or [pat])]
        docs = [normalize(p) for p in paths]
        if args.out:
            os.makedirs(args.out, exist_ok=True)
            for d in docs:
                name = os.path.splitext(str(d.get("source")))[0].lower()
                dst = os.path.join(args.out, name + ".canonical.json")
                with open(dst, "w") as f:
                    json.dump(d, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(dst)
        else:
            json.dump(docs if len(docs) > 1 else docs[0],
                      sys.stdout, indent=1, sort_keys=True)
            print()
        return 0

    if not args.candidate:
        ap.error("need --candidate (or --normalize / --smoke)")
    candidate = merge_rounds([normalize(p) for p in args.candidate])
    if not os.path.exists(args.baseline):
        sys.stderr.write("sentinel: no baseline at %s\n" % args.baseline)
        if args.update_baseline:
            os.makedirs(os.path.dirname(args.baseline) or ".",
                        exist_ok=True)
            with open(args.baseline, "w") as f:
                json.dump(candidate, f, indent=1, sort_keys=True)
                f.write("\n")
            sys.stderr.write("sentinel: seeded baseline from candidate\n")
            return 0
        return 2
    with open(args.baseline) as f:
        baseline = json.load(f)

    rows = compare(baseline, candidate)
    if args.format == "json":
        out = {"baseline": baseline.get("source"),
               "candidate": candidate.get("source"),
               "rows": rows, "regression": bool(verdict_exit(rows))}
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        sys.stdout.write(markdown_table(rows, baseline, candidate))

    rc = verdict_exit(rows)
    if args.update_baseline:
        if rc == 0:
            with open(args.baseline, "w") as f:
                json.dump(candidate, f, indent=1, sort_keys=True)
                f.write("\n")
            sys.stderr.write("sentinel: baseline updated\n")
        else:
            sys.stderr.write(
                "sentinel: refusing to update baseline over a FAIL "
                "(fix or edit %s manually)\n" % args.baseline)
    return rc


if __name__ == "__main__":
    sys.exit(main())
