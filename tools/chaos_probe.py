#!/usr/bin/env python
"""Probe: dist_async training under chaos wire faults must still converge.

Launches a 1-server/2-worker gang with the chaos harness dropping 10% of
all KVStore frames (both directions).  Every dropped frame forces a
client timeout -> reconnect -> replay; the server's (rank, seq) dedup
makes the replays idempotent.  Acceptance: both workers converge, the
gang exits clean with zero leftover processes, and the workers actually
exercised the retry path (retries > 0 — a probe that never saw a fault
proves nothing).

Usage:
    python tools/chaos_probe.py --smoke   # ~30s, CPU
    python tools/chaos_probe.py           # longer run, higher drop count
"""
import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_main():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, telemetry

    kv = mx.kv.create("dist_async")
    rank = kv.rank
    steps = int(os.environ["CHAOS_PROBE_STEPS"])

    rng = np.random.RandomState(100 + rank)
    w_true = np.array([[1.0], [-2.0], [3.0]], np.float32)
    X = rng.randn(128, 3).astype(np.float32)
    y = X @ w_true

    kv.init("w", nd.zeros((3, 1)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))
    kv.barrier()
    w = nd.zeros((3, 1))
    for step in range(steps):
        kv.pull("w", out=w)
        i = (step * 32) % 96
        xb, yb = nd.array(X[i:i + 32]), nd.array(y[i:i + 32])
        kv.push("w", nd.dot(xb.T, nd.dot(xb, w) - yb) / 32)
    kv.barrier()
    kv.pull("w", out=w)
    err = float(np.abs(w.asnumpy() - w_true).max())
    snap = telemetry.snapshot()

    def total(name):
        fam = snap.get(name) or {}
        return float(sum(s.get("value", 0)
                         for s in fam.get("samples", ())))

    print(json.dumps({"rank": rank, "err": err,
                      "retries": total("kvstore_retries_total"),
                      "reconnects": total("kvstore_reconnects_total"),
                      "timeouts": total("kvstore_op_timeout_total")}))
    # no stop command here: under active chaos the shutdown coda races
    # (a dropped final ack leaves the peer retrying against a stopped
    # server), so the LAUNCHER stops the server after both workers exit
    kv.close()
    sys.exit(0 if err < 0.05 else 1)


def main(argv):
    role = os.environ.get("CHAOS_PROBE_ROLE")
    if role == "server":
        os.environ["DMLC_ROLE"] = "server"
        import mxnet_tpu as mx
        mx.kv.create("dist_async")      # run_server(); returns on stop
        return 0
    if role == "worker":
        _worker_main()
        return 0

    smoke = "--smoke" in argv
    steps = 60 if smoke else 300
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "MXNET_PS_URI": "127.0.0.1",
        "MXNET_PS_PORT": str(_free_port()),
        "DMLC_NUM_WORKER": "2",
        "CHAOS_PROBE_STEPS": str(steps),
        "MXNET_CHAOS": "1",
        "MXNET_CHAOS_SEED": "1",
        "MXNET_CHAOS_FRAME_DROP_P": "0.10",
        # every dropped frame costs one op timeout before the replay, so
        # the smoke keeps the deadline tight to bound wall-clock
        "MXNET_KVSTORE_OP_TIMEOUT": "0.5" if smoke else "2",
        # the barrier deadline defaults to 600s (real stragglers are
        # slow); under injected drops that IS the hang we are probing
        # for, so bound it too
        "MXNET_KVSTORE_BARRIER_TIMEOUT": "5" if smoke else "30",
        "MXNET_KVSTORE_MAX_RETRIES": "8",
        "MXNET_KVSTORE_RETRY_BACKOFF": "0.02",
    })
    me = os.path.abspath(__file__)
    procs = []
    senv = dict(env)
    senv["CHAOS_PROBE_ROLE"] = "server"
    procs.append(subprocess.Popen([sys.executable, me], env=senv))
    wout = []
    for wid in range(2):
        wenv = dict(env)
        wenv.update({"CHAOS_PROBE_ROLE": "worker",
                     "DMLC_ROLE": "worker", "DMLC_WORKER_ID": str(wid)})
        procs.append(subprocess.Popen([sys.executable, me], env=wenv,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True))
        wout.append(procs[-1])
    server_proc = procs[0]
    rcs = [None]
    try:
        for p in procs[1:]:
            rcs.append(p.wait(timeout=600 if smoke else 1800))
        # workers are done: stop the server with a clean (chaos-free,
        # this process never set MXNET_CHAOS) stop frame
        from mxnet_tpu.kvstore_server import send_msg
        s = socket.create_connection(
            ("127.0.0.1", int(env["MXNET_PS_PORT"])), timeout=30)
        send_msg(s, ["stop"])
        s.close()
        rcs[0] = server_proc.wait(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outputs = [p.stdout.read() for p in wout]
    if any(rc != 0 for rc in rcs):
        for i, out in enumerate(outputs):
            print("--- worker %d output ---\n%s" % (i, out[-4000:]))
        raise AssertionError("gang exited dirty: %s" % rcs)
    results = []
    for out in outputs:
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        results.append(json.loads(line))
    retries = sum(r["retries"] for r in results)
    max_err = max(r["err"] for r in results)
    assert max_err < 0.05, "did not converge under 10%% drop: %s" % results
    assert retries > 0, \
        "no retries recorded — the fault injection never fired: %s" % results
    print(json.dumps({"probe": "chaos", "ok": True, "smoke": smoke,
                      "steps": steps, "frame_drop_p": 0.10,
                      "max_err": max_err, "retries": retries,
                      "reconnects": sum(r["reconnects"] for r in results)}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
