#!/usr/bin/env python
"""Input-pipeline benchmark: proves ImageRecordIter decode throughput
against the training-step rate (VERDICT round-1 weak #5: the data pipeline
must keep up with the compute step at batch 128 / 224px).

Builds (once) a synthetic JPEG .rec, then measures batches/s with the
thread-pool decoder at several thread counts.  Prints one JSON line per
configuration:

    {"metric": "imagerecorditer_img_per_sec", "value": ..., "threads": N, ...}

Ref analog: src/io/iter_image_recordio_2.cc:727 (N decode threads) and
tools/bandwidth (measurement harness pattern).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# host-pipeline benchmark: batches must stay on CPU — an accelerator
# context would time device transfer (pathological over a tunnel), not
# decode.  In-process config update beats env (sitecustomize may have
# already imported jax with a pinned platform).
import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import recordio


def build_rec(prefix, num_images=512, size=256, seed=0):
    rec_path, idx_path = prefix + ".rec", prefix + ".idx"
    if os.path.exists(rec_path) and os.path.exists(idx_path):
        return rec_path, idx_path
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(num_images):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return rec_path, idx_path


def measure(rec_path, idx_path, batch_size, image_size, threads, epochs=2,
            prefetch=2, pipelined=True):
    """img/s through ImageRecordIter; ``pipelined`` wraps it in the
    worker-pool PrefetchingIter (the product train-loop path) so the
    measurement includes ordered reassembly + staging-buffer reuse, not
    just raw decode."""
    it = mx.io.ImageRecordIter(
        rec_path, (3, image_size, image_size), batch_size,
        path_imgidx=idx_path, shuffle=True, rand_crop=True,
        rand_mirror=True, resize=image_size + 32,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        preprocess_threads=threads, prefetch_buffer=prefetch)
    inner = it
    if pipelined:
        it = mx.io.PrefetchingIter(it, num_workers=2,
                                   prefetch_depth=prefetch)
    # warm epoch (thread pool spin-up, page cache)
    for _ in it:
        pass
    n = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for batch in it:
            n += batch.data[0].shape[0] - batch.pad
    dt = time.perf_counter() - t0
    inner.close()
    return n / dt


def smoke():
    """Schema guard for CI: tiny dataset, one pipelined + one unpipelined
    measurement, assert the JSON line fields exist and the two paths
    deliver the same per-epoch image count (no dup/drop under overlap)."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        rec_path, idx_path = build_rec(os.path.join(d, "smoke"),
                                       num_images=48, size=64)
        for pipelined in (False, True):
            ips = measure(rec_path, idx_path, batch_size=16, image_size=48,
                          threads=2, epochs=1, pipelined=pipelined)
            line = {"metric": "imagerecorditer_img_per_sec",
                    "value": round(ips, 2), "unit": "img/s", "threads": 2,
                    "batch": 16, "image": 48, "pipelined": pipelined,
                    "host_cpus": os.cpu_count()}
            for key in ("metric", "value", "unit", "threads", "batch",
                        "image", "pipelined", "host_cpus"):
                assert key in line and line[key] is not None, key
            assert ips > 0, "no images decoded"
            print(json.dumps(line))
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-images", type=int, default=512)
    ap.add_argument("--threads", default="1,4,8")
    ap.add_argument("--prefix", default="/tmp/bench_io_data")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="measure the bare iterator without the "
                         "PrefetchingIter worker pool")
    ap.add_argument("--smoke", action="store_true",
                    help="CI schema guard: tiny run, assert output shape")
    ap.add_argument("--target", type=float, default=0.0,
                    help="training-step img/s to compare against "
                         "(e.g. the bench.py number)")
    args = ap.parse_args()
    if args.smoke:
        return smoke()

    rec_path, idx_path = build_rec(args.prefix, args.num_images)
    for t in [int(x) for x in args.threads.split(",")]:
        ips = measure(rec_path, idx_path, args.batch_size, args.image_size,
                      t, pipelined=not args.no_pipeline)
        line = {"metric": "imagerecorditer_img_per_sec",
                "value": round(ips, 2), "unit": "img/s", "threads": t,
                "batch": args.batch_size, "image": args.image_size,
                "pipelined": not args.no_pipeline,
                "host_cpus": os.cpu_count()}
        if args.target > 0:
            line["keeps_up_with_step"] = ips >= args.target
        print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
