#!/usr/bin/env python
"""Measure kvstore/collective communication bandwidth.

Reference analog: ``tools/bandwidth/`` (SURVEY.md §6 benchmark harnesses) —
measures the gradient-aggregation path's throughput.  Here: the XLA
all-reduce over the device mesh (ICI) and, under a multi-process launch,
the cross-process DCN all-reduce used by dist_sync.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def bench_device_allreduce(size_mb: float, iters: int) -> float:
    """All-reduce over all local devices via psum (the kvstore 'device'
    path); returns GB/s of algorithmic bandwidth."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax import shard_map

    devs = jax.local_devices()
    n = len(devs)
    if n < 2:
        raise SystemExit("device all-reduce needs >= 2 devices (have %d); "
                         "use XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=N on CPU" % n)
    elems = int(size_mb * 1e6 / 4)
    mesh = Mesh(np.asarray(devs), ("d",))
    x = jnp.ones((n, elems), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("d")))
    f = jax.jit(shard_map(lambda v: jax.lax.psum(v, "d"), mesh=mesh,
                          in_specs=P("d"), out_specs=P("d")))
    f(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(x)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    # ring all-reduce moves 2(n-1)/n of the data per device
    gbytes = iters * elems * 4 * 2 * (n - 1) / n / 1e9
    return gbytes / dt


def bench_dist_allreduce(size_mb: float, iters: int) -> float:
    """Cross-process all-reduce (the dist_sync path); run under
    tools/launch.py -n W."""
    from mxnet_tpu.parallel import process_group
    import jax.numpy as jnp

    pg = process_group()
    if pg.size < 2:
        raise SystemExit("dist all-reduce needs >= 2 processes — run under "
                         "tools/launch.py -n W (single-process allreduce "
                         "is an identity; there is nothing to measure)")
    elems = int(size_mb * 1e6 / 4)
    x = jnp.ones((elems,), jnp.float32)
    pg.allreduce(x)                       # warm the compiled collective
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pg.allreduce(x)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    n = pg.size
    gbytes = iters * elems * 4 * 2 * max(n - 1, 1) / max(n, 1) / 1e9
    return gbytes / dt


def bench_ps(iters: int):
    """Parameter-server push/pull throughput vs payload size (VERDICT r4
    item 4: the dist_async wire had no measured number).  In-process
    server on loopback — measures the codec + TCP + server-apply path,
    an upper bound on what a real NIC would see."""
    import json

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.kvstore_server import KVStoreServer

    srv = KVStoreServer(num_workers=1).start()
    os.environ["MXNET_PS_URI"] = "127.0.0.1"
    os.environ["MXNET_PS_PORT"] = str(srv.port)
    os.environ["DMLC_NUM_WORKER"] = "1"
    rows = []
    try:
        kv = mx.kv.create("dist_async")
        for size_mb in (0.25, 1.0, 4.0, 16.0, 64.0):
            n = int(size_mb * 1e6 / 4)
            key = "k%g" % size_mb
            x = nd.array(np.ones(n, np.float32))
            kv.init(key, x)
            out = nd.zeros((n,))
            row = {"size_mb": size_mb}
            for name, fn in (("push", lambda: kv.push(key, x)),
                             ("pull", lambda: kv.pull(key, out=out))):
                fn()                                   # warm
                t0 = time.perf_counter()
                for _ in range(iters):
                    fn()
                dt = time.perf_counter() - t0
                row[name + "_gbps"] = round(
                    iters * n * 4 / dt / 1e9, 3)
            # compressed push: same logical payload, 1/16 wire bytes
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
            kv.push(key, x)
            t0 = time.perf_counter()
            for _ in range(iters):
                kv.push(key, x)
            dt = time.perf_counter() - t0
            row["push_2bit_logical_gbps"] = round(
                iters * n * 4 / dt / 1e9, 3)
            kv.set_gradient_compression(None)          # off for next size
            rows.append(row)
        kv.close()
    finally:
        srv.shutdown()
    print(json.dumps({"metric": "ps_bandwidth", "iters": iters,
                      "rows": rows}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--mode", choices=["device", "dist", "ps"],
                    default="device")
    args = ap.parse_args()
    if args.mode == "device":
        bw = bench_device_allreduce(args.size_mb, args.iters)
        print("device all-reduce (%g MB x %d): %.2f GB/s"
              % (args.size_mb, args.iters, bw))
    elif args.mode == "ps":
        bench_ps(args.iters)
    else:
        bw = bench_dist_allreduce(args.size_mb, args.iters)
        print("dist all-reduce (%g MB x %d): %.2f GB/s"
              % (args.size_mb, args.iters, bw))


if __name__ == "__main__":
    main()
