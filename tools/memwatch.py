#!/usr/bin/env python
"""Memory-ledger CLI: snapshot, watch, diff and smoke-check memwatch.

Modes:

``snapshot`` (default)
    GET ``/memz`` from a running job's telemetry endpoint and render the
    owner ledger, per-device allocator stats and leak-suspects table.
    ``--refresh`` forces a fresh census server-side; ``-o FILE`` saves
    the raw JSON for a later ``--diff``.

``--watch [SECS]``
    Poll the endpoint and reprint the ledger with per-owner deltas —
    a top(1) for device memory.

``--diff A B``
    Two saved snapshots -> per-owner / per-device byte deltas plus the
    suspects that appeared in B.  The forensic workflow: snapshot before
    and after the suspect window, diff, read the growth.

``--smoke``
    Self-contained in-process check (no server): enable memwatch, run a
    tiny train loop through Module, then assert the acceptance contract
    — tagged coverage >= 90% of census bytes, zero leak suspects, and
    an OOM pre-flight verdict that passes under a roomy synthetic
    ``bytes_limit`` and trips under a 1-byte one.  Exit 0/1.

Usage:
    python tools/memwatch.py [--url http://127.0.0.1:9102] [--refresh]
    python tools/memwatch.py -o before.json
    python tools/memwatch.py --watch 5
    python tools/memwatch.py --diff before.json after.json
    python tools/memwatch.py --smoke
"""
import argparse
import json
import os
import sys
import time
import urllib.request


def _fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return "%.1f %s" % (n, unit) if unit != "B" \
                else "%d B" % int(n)
        n /= 1024.0


def _default_url():
    port = os.environ.get("MXNET_TELEMETRY_PORT")
    return "http://127.0.0.1:%s" % port if port else "http://127.0.0.1:9102"


def _fetch(url, refresh):
    full = url.rstrip("/") + "/memz" + ("?refresh=1" if refresh else "")
    with urllib.request.urlopen(full, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _render(snap, prev=None, out=sys.stdout):
    w = out.write
    w("memwatch @ %s  gen=%s  coverage=%.2f%%  enabled=%s\n"
      % (time.strftime("%H:%M:%S",
                       time.localtime(snap.get("unix_time", time.time()))),
         snap.get("generation"), snap.get("coverage_pct", 0.0),
         snap.get("enabled")))
    w("%-12s %14s %8s %12s\n" % ("owner", "bytes", "arrays", "delta"))
    prev_owners = (prev or {}).get("owners", {})
    for owner, rec in snap.get("owners", {}).items():
        delta = rec["bytes"] - prev_owners.get(owner, {}).get("bytes", 0) \
            if prev else 0
        w("%-12s %14s %8d %12s\n"
          % (owner, _fmt_bytes(rec["bytes"]), rec["arrays"],
             ("%+d" % delta) if prev else "-"))
    for dev, st in snap.get("devices", {}).items():
        w("device %-24s in_use=%s peak=%s limit=%s (%s)\n"
          % (dev, _fmt_bytes(st["bytes_in_use"]),
             _fmt_bytes(st["peak_bytes_in_use"]),
             _fmt_bytes(st["bytes_limit"]) if st["bytes_limit"] else "-",
             st.get("source", "?")))
    suspects = snap.get("suspects", [])
    if suspects:
        w("leak suspects (age >= sentinel window):\n")
        for s in suspects:
            w("  %10s  shape=%s dtype=%s device=%s age=%d likely=%s\n"
              % (_fmt_bytes(s["nbytes"]), s["shape"], s["dtype"],
                 s["device"], s["age"], s.get("likely_owner")))
    out.flush()


def _diff(path_a, path_b, out=sys.stdout):
    with open(path_a) as f:
        a = json.load(f)
    with open(path_b) as f:
        b = json.load(f)
    w = out.write
    w("diff %s -> %s\n" % (path_a, path_b))
    w("%-12s %14s %14s %14s\n" % ("owner", "before", "after", "delta"))
    owners = sorted(set(a.get("owners", {})) | set(b.get("owners", {})))
    for owner in owners:
        ba = a.get("owners", {}).get(owner, {}).get("bytes", 0)
        bb = b.get("owners", {}).get(owner, {}).get("bytes", 0)
        w("%-12s %14s %14s %+14d\n"
          % (owner, _fmt_bytes(ba), _fmt_bytes(bb), bb - ba))
    devs = sorted(set(a.get("devices", {})) | set(b.get("devices", {})))
    for dev in devs:
        da = a.get("devices", {}).get(dev, {}).get("bytes_in_use", 0)
        db = b.get("devices", {}).get(dev, {}).get("bytes_in_use", 0)
        w("device %-24s %14s %14s %+14d\n"
          % (dev, _fmt_bytes(da), _fmt_bytes(db), db - da))
    old_ids = {s["id"] for s in a.get("suspects", [])}
    new = [s for s in b.get("suspects", []) if s["id"] not in old_ids]
    if new:
        w("new leak suspects in %s:\n" % path_b)
        for s in new:
            w("  %10s  shape=%s dtype=%s device=%s likely=%s\n"
              % (_fmt_bytes(s["nbytes"]), s["shape"], s["dtype"],
                 s["device"], s.get("likely_owner")))
    out.flush()
    return 0


def _smoke():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import health, memwatch, storage

    memwatch.reset()
    health.enable()
    memwatch.enable(census_thread=False)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    batch = mx.io.DataBatch(
        data=[mx.nd.array(np.random.rand(8, 32).astype("float32"))],
        label=[mx.nd.array(
            np.random.randint(0, 4, (8,)).astype("float32"))])
    mod.bind(data_shapes=[("data", (8, 32))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    for _ in range(3):
        mod.forward(batch)
        mod.backward()
        mod.update()

    snap = memwatch.census()
    failures = []
    if snap["coverage_pct"] < 90.0:
        failures.append("coverage %.2f%% < 90%%" % snap["coverage_pct"])
    if snap["suspects"]:
        failures.append("leak suspects present: %r" % snap["suspects"])

    # pre-flight: CPU backends expose no allocator limit, so exercise the
    # projection against synthetic limits — roomy must pass, 1 byte must
    # trip.
    pcs = health.programs()
    verdicts = {}
    if pcs:
        pc = next(iter(pcs.values()))
        real_limit = storage.bytes_limit
        try:
            storage.bytes_limit = lambda device=None: 1 << 40
            roomy = memwatch.preflight(pc)
            storage.bytes_limit = lambda device=None: 1
            tight = memwatch.preflight(pc)
        finally:
            storage.bytes_limit = real_limit
        verdicts = {"roomy": roomy, "tight": tight}
        if roomy is None or roomy["risk"]:
            failures.append("pre-flight flagged a tiny program against a "
                            "1 TiB limit: %r" % (roomy,))
        if tight is None or not tight["risk"]:
            failures.append("pre-flight missed a 1-byte limit: %r"
                            % (tight,))
    else:
        failures.append("no program registered with health — pre-flight "
                        "never exercised")

    print(json.dumps({
        "probe": "memwatch", "ok": not failures, "failures": failures,
        "coverage_pct": round(snap["coverage_pct"], 2),
        "owners": {o: rec["bytes"] for o, rec in snap["owners"].items()},
        "suspects": len(snap["suspects"]),
        "preflight": {k: (v and {"risk": v["risk"],
                                 "need_bytes": v["need_bytes"]})
                      for k, v in verdicts.items()},
    }))
    return 0 if not failures else 1


def main(argv):
    ap = argparse.ArgumentParser(
        description="memwatch ledger CLI (see module docstring)")
    ap.add_argument("--url", default=_default_url(),
                    help="telemetry endpoint base URL")
    ap.add_argument("--refresh", action="store_true",
                    help="force a fresh census server-side")
    ap.add_argument("-o", "--output", metavar="FILE",
                    help="save the raw snapshot JSON")
    ap.add_argument("--watch", nargs="?", const=5.0, type=float,
                    metavar="SECS", help="poll and reprint with deltas")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="diff two saved snapshot files")
    ap.add_argument("--smoke", action="store_true",
                    help="in-process acceptance smoke (no server needed)")
    args = ap.parse_args(argv)

    if args.smoke:
        return _smoke()
    if args.diff:
        return _diff(args.diff[0], args.diff[1])
    if args.watch is not None:
        prev = None
        try:
            while True:
                snap = _fetch(args.url, refresh=True)
                _render(snap, prev=prev)
                sys.stdout.write("\n")
                prev = snap
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0
    snap = _fetch(args.url, args.refresh)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(snap, f, indent=2, default=str)
        print("saved %s" % args.output)
    _render(snap)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
