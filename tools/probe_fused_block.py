#!/usr/bin/env python
"""Probe: hand-fused Pallas ResNet bottleneck block vs XLA scheduling.

Round-3 verdict item 3 — the last unprobed ResNet lever.  r03 measured a
~2x in-graph-vs-isolated conv gap (convs run 150-195 TF isolated but ~45
TF aggregate inside the ResNet step) and blamed XLA:axon's in-graph
scheduling.  This probe hand-schedules EXACTLY the region the trace
blames: one full bottleneck block (1x1 512->128, 3x3 128->128 via 9
shifted GEMMs, 1x1 128->512, inference-folded BN biases, ReLUs, residual
add) as ONE Pallas kernel with every intermediate resident in VMEM —
zero HBM traffic between the three convs — against the identical math
left to XLA.  Both run as a 16-block chain (out feeds in), reproducing
the in-graph scheduling regime the whole-model trace shows; single-block
(isolated) numbers are recorded too.

If the fused kernel wins >=15% the block is worth wiring behind a flag;
if XLA wins, "platform-bound at ~2,500 img/s" graduates from hypothesis
to measurement (the scheduling gap is not recoverable by hand-fusing the
hot region either).

Run: python tools/probe_fused_block.py
"""
import functools
import json
import sys
import time

import numpy as np

REPS = 7
CHAIN = 16
N, HW, C_IN, C_MID = 32, 28, 512, 128    # the 28x28 bottleneck stage
TB = 2                                   # batch tile resident in VMEM


def _kernel(x_ref, w1_ref, w2_ref, w3_ref, b_ref, o_ref):
    import jax
    import jax.numpy as jnp

    x0 = x_ref[0]                                    # (TB*784, 512) bf16
    f32 = jnp.float32
    # conv1 1x1 + bias + relu  (BN pre-folded into weights/bias)
    h1 = jax.lax.dot_general(x0, w1_ref[...],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=f32)
    h1 = jnp.maximum(h1 + b_ref[0, :C_MID], 0.0).astype(x0.dtype)
    # conv2 3x3 as 9 shifted GEMMs on the padded (TB,30,30,128) map
    h1r = h1.reshape(TB, HW, HW, C_MID)
    h1p = jnp.pad(h1r, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((TB * HW * HW, C_MID), f32)
    for dy in range(3):
        for dx in range(3):
            tap = h1p[:, dy:dy + HW, dx:dx + HW, :] \
                .reshape(TB * HW * HW, C_MID)
            acc += jax.lax.dot_general(
                tap, w2_ref[3 * dy + dx],
                (((1,), (0,)), ((), ())), preferred_element_type=f32)
    h2 = jnp.maximum(acc + b_ref[1, :C_MID], 0.0).astype(x0.dtype)
    # conv3 1x1 + bias + residual + relu
    h3 = jax.lax.dot_general(h2, w3_ref[...],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=f32)
    h3 = h3 + b_ref[2] + x0.astype(f32)
    o_ref[0] = jnp.maximum(h3, 0.0).astype(o_ref.dtype)


def fused_block(x, w1, w2, w3, b):
    """x: (N*784, 512) bf16 -> same; one pallas_call, batch-tiled."""
    import jax
    from jax.experimental import pallas as pl

    rows = TB * HW * HW
    nt = (N * HW * HW) // rows
    return pl.pallas_call(
        _kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, rows, C_IN), lambda t: (t, 0, 0)),
            pl.BlockSpec((C_IN, C_MID), lambda t: (0, 0)),
            pl.BlockSpec((9, C_MID, C_MID), lambda t: (0, 0, 0)),
            pl.BlockSpec((C_MID, C_IN), lambda t: (0, 0)),
            pl.BlockSpec((3, C_IN), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, rows, C_IN), lambda t: (t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nt, rows, C_IN), x.dtype),
    )(x.reshape(nt, rows, C_IN), w1, w2, w3, b).reshape(N * HW * HW, C_IN)


def xla_block(x, w1, w2, w3, b):
    """Identical math, XLA-scheduled (same shifted-GEMM formulation AND
    the lax.conv formulation is measured separately below)."""
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    h1 = jnp.maximum(
        jax.lax.dot_general(x, w1, (((1,), (0,)), ((), ())),
                            preferred_element_type=f32) + b[0, :C_MID],
        0.0).astype(x.dtype)
    h1p = jnp.pad(h1.reshape(N, HW, HW, C_MID),
                  ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((N * HW * HW, C_MID), f32)
    for dy in range(3):
        for dx in range(3):
            tap = h1p[:, dy:dy + HW, dx:dx + HW, :] \
                .reshape(N * HW * HW, C_MID)
            acc += jax.lax.dot_general(
                tap, w2[3 * dy + dx], (((1,), (0,)), ((), ())),
                preferred_element_type=f32)
    h2 = jnp.maximum(acc + b[1, :C_MID], 0.0).astype(x.dtype)
    h3 = jax.lax.dot_general(h2, w3, (((1,), (0,)), ((), ())),
                             preferred_element_type=f32) \
        + b[2] + x.astype(f32)
    return jnp.maximum(h3, 0.0).astype(x.dtype)


def xla_block_conv(x, w1, w2, w3, b):
    """Same block through lax.conv_general_dilated (what the model zoo
    lowers to), NHWC."""
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    xi = x.reshape(N, HW, HW, C_IN)
    dn = ("NHWC", "HWIO", "NHWC")
    h1 = jnp.maximum(jax.lax.conv_general_dilated(
        xi, w1.reshape(1, 1, C_IN, C_MID), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=f32)
        + b[0, :C_MID], 0.0).astype(x.dtype)
    h2 = jnp.maximum(jax.lax.conv_general_dilated(
        h1, w2.reshape(3, 3, C_MID, C_MID), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=f32)
        + b[1, :C_MID], 0.0).astype(x.dtype)
    h3 = jax.lax.conv_general_dilated(
        h2, w3.reshape(1, 1, C_MID, C_IN), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=f32) \
        + b[2] + xi.astype(f32)
    return jnp.maximum(h3, 0.0).astype(x.dtype).reshape(N * HW * HW, C_IN)


def xla_block_conv_trainbn(x, w1, w2, w3, b):
    """The conv block as the TRAINING graph sees it: live batch-norm
    statistics (mean/var reductions + normalize) after each conv instead
    of folded biases — isolates how much of the whole-model in-graph
    ~45 TF aggregate is BN, not conv scheduling."""
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    dn = ("NHWC", "HWIO", "NHWC")

    def bn_relu(h, relu=True):
        m = jnp.mean(h, axis=(0, 1, 2), keepdims=True)
        v = jnp.mean(jnp.square(h - m), axis=(0, 1, 2), keepdims=True)
        out = (h - m) * jax.lax.rsqrt(v + 1e-5)
        return (jnp.maximum(out, 0.0) if relu else out)

    xi = x.reshape(N, HW, HW, C_IN)
    h1 = bn_relu(jax.lax.conv_general_dilated(
        xi, w1.reshape(1, 1, C_IN, C_MID), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=f32)).astype(x.dtype)
    h2 = bn_relu(jax.lax.conv_general_dilated(
        h1, w2.reshape(3, 3, C_MID, C_MID), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=f32)).astype(x.dtype)
    h3 = bn_relu(jax.lax.conv_general_dilated(
        h2, w3.reshape(1, 1, C_MID, C_IN), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=f32), relu=False)
    return jnp.maximum(h3 + xi.astype(f32), 0.0).astype(x.dtype) \
        .reshape(N * HW * HW, C_IN)


def main():
    import jax
    import jax.numpy as jnp
    import statistics

    r = np.random.default_rng(0)
    x = jnp.asarray(r.standard_normal((N * HW * HW, C_IN)) * 0.5,
                    jnp.bfloat16)
    w1 = jnp.asarray(r.standard_normal((C_IN, C_MID)) * 0.05, jnp.bfloat16)
    w2 = jnp.asarray(r.standard_normal((9, C_MID, C_MID)) * 0.05,
                     jnp.bfloat16)
    w3 = jnp.asarray(r.standard_normal((C_MID, C_IN)) * 0.05, jnp.bfloat16)
    b = jnp.asarray(r.standard_normal((3, C_IN)) * 0.1, jnp.float32)

    flops_block = 2 * N * HW * HW * (C_IN * C_MID * 2 + 9 * C_MID * C_MID)

    def timed(block_fn, chain):
        """Differential (2N - N chains, median of paired differences):
        cancels the ~100 ms tunnel RTT that otherwise swamps ms-scale
        blocks."""
        def build(n):
            @jax.jit
            def f(x0):
                def body(c, _):
                    return block_fn(c, w1, w2, w3, b), None
                y, _ = jax.lax.scan(body, x0, None, length=n)
                return jnp.sum(y.astype(jnp.float32))
            return f
        f1, f2 = build(chain), build(2 * chain)
        float(f1(x)); float(f2(x))
        diffs = []
        for _ in range(REPS):
            t0 = time.perf_counter(); float(f1(x))
            d1 = time.perf_counter() - t0
            t0 = time.perf_counter(); float(f2(x))
            diffs.append((time.perf_counter() - t0) - d1)
        med = statistics.median(diffs)
        return med / chain if med > 0 else None

    out = {"metric": "fused_bottleneck_probe",
           "shape": "28x28, 512->128->128->512, batch %d, bf16" % N,
           "gflops_per_block": round(flops_block / 1e9, 2)}
    rows = {}
    try:
        # one shared reference; a conv-lowering failure must not erase
        # the other formulations' rows
        ref = np.asarray(xla_block_conv(x, w1, w2, w3, b)
                         .astype(jnp.float32))
    except Exception as e:
        ref = None
        rows["xla_conv_reference_error"] = repr(e)[:300]
    for name, fn in (("pallas_fused", fused_block),
                     ("xla_shifted_gemm", xla_block),
                     ("xla_conv", xla_block_conv),
                     ("xla_conv_trainbn", xla_block_conv_trainbn)):
        try:
            # exactness vs the conv formulation (trainbn computes
            # different math by design — err is informational there)
            got = np.asarray(fn(x, w1, w2, w3, b).astype(jnp.float32))
            err = (float(np.max(np.abs(got - ref)))
                   if ref is not None else None)
            t_chain = timed(fn, CHAIN)
            t_iso = timed(fn, 1)
            rows[name] = {"max_err_vs_conv": err}
            if t_chain is not None:
                rows[name].update(
                    chain16_ms_per_block=round(t_chain * 1e3, 3),
                    chain16_tf=round(flops_block / t_chain / 1e12, 1))
            else:
                rows[name]["chain_timing_suspect"] = True
            if t_iso is not None:
                rows[name].update(
                    isolated_ms=round(t_iso * 1e3, 3),
                    isolated_tf=round(flops_block / t_iso / 1e12, 1))
        except Exception as e:
            rows[name] = {"error": repr(e)[:300]}
    out.update(rows)
    pf, xc = rows.get("pallas_fused", {}), rows.get("xla_conv", {})
    if "chain16_ms_per_block" in pf and "chain16_ms_per_block" in xc:
        out["fused_vs_xla_conv_chain"] = round(
            xc["chain16_ms_per_block"] / pf["chain16_ms_per_block"], 3)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, "/root/repo")
    sys.exit(main())
