#!/usr/bin/env python
"""Probe the three targeted conv fixes found by probe_resnet_step.py:

1. stem 7x7s2 C=3 -> space-to-depth(2) + 4x4s1 C=12 (exact rewrite)
2. strided 1x1 projection  -> slice x[::2,::2] then dense 1x1 matmul
3. 1x1 wgrad at 56x56 64<->256 -> Pallas reduction-GEMM kernel

Run:  python tools/probe_conv_fixes.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

REPS = 4


def time_chain(step, x0, chain):
    def build(n):
        @jax.jit
        def f(x):
            def body(c, _):
                return step(c) * jnp.bfloat16(0.25), None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(y.astype(jnp.float32))
        return f
    f1, f2 = build(chain), build(2 * chain)
    float(f1(x0)); float(f2(x0))
    best1 = best2 = 1e9
    for _ in range(REPS):
        t0 = time.perf_counter(); float(f1(x0))
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter(); float(f2(x0))
        best2 = min(best2, time.perf_counter() - t0)
    return max(best2 - best1, 1e-9) / chain




def up2(y, H):
    """Exact 2x nearest upsample via broadcast (cheap, fusion-friendly)."""
    N, h, w, C = y.shape
    y = jnp.broadcast_to(y[:, :, None, :, None, :], (N, h, 2, w, 2, C))
    return y.reshape(N, 2 * h, 2 * w, C)

def conv(x, w, s=1, pad="SAME"):
    return jax.lax.conv_general_dilated(
        x, w, (s, s), pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))


def space_to_depth(x, b=2):
    N, H, W, C = x.shape
    x = x.reshape(N, H // b, b, W // b, b, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(N, H // b, W // b, b * b * C)


def stem_s2d_weights(w):
    """(7,7,3,64) -> (4,4,12,64) operating on space-to-depth(2) input.

    y[ho,wo] = sum_{dh,dw} x[2ho+dh-3, 2wo+dw-3] w[dh,dw].  Write
    dh-3 = 2e+p (p in {0,1}); then tap (e,p) multiplies s2d channel p at
    spatial offset ho+e, e in [-2,1] -> a 4x4 stride-1 conv over the
    (112,112,12) s2d input, padded by 2 low / 1 high.
    """
    w4 = np.zeros((4, 4, 12, w.shape[3]), np.float32)
    wn = np.asarray(w, np.float32)
    for dh in range(7):
        e_h, p_h = divmod(dh - 3, 2)       # x[2ho+dh-3] = s2d[ho+e_h, p_h]
        for dw in range(7):
            e_w, p_w = divmod(dw - 3, 2)
            # s2d channel layout: (p, q, c) -> p*2*3 + q*3 + c
            for c in range(3):
                w4[e_h + 2, e_w + 2, p_h * 6 + p_w * 3 + c] += wn[dh, dw, c]
    return jnp.asarray(w4, w.dtype)


def main():
    N = 128
    rng = np.random.default_rng(0)

    # ---------------- 1. stem --------------------------------------
    x = jnp.asarray(rng.standard_normal((N, 224, 224, 3)) * 0.1, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((7, 7, 3, 64)) * 0.1, jnp.bfloat16)
    flops = 2 * N * 112 * 112 * 3 * 64 * 49
    mixw = jnp.asarray(rng.standard_normal((1, 1, 64, 3)) * 0.1, jnp.bfloat16)

    def stem_ref(c):
        y = jax.nn.relu(conv(c, w, 2))
        y = conv(y, mixw)
        return up2(y, 224)

    w4 = stem_s2d_weights(w)

    def stem_s2d(c):
        xs = space_to_depth(c, 2)                       # (N,112,112,12)
        xs = jnp.pad(xs, ((0, 0), (2, 1), (2, 1), (0, 0)))
        y = jax.nn.relu(conv(xs, w4, 1, "VALID"))
        y = conv(y, mixw)
        return up2(y, 224)

    ref = np.asarray(conv(x, w, 2).astype(jnp.float32))
    xs = jnp.pad(space_to_depth(x, 2), ((0, 0), (2, 1), (2, 1), (0, 0)))
    got = np.asarray(conv(xs, w4, 1, "VALID").astype(jnp.float32))
    err = np.max(np.abs(got - ref)) / np.max(np.abs(ref))
    t0 = time_chain(stem_ref, x, 64)
    t1 = time_chain(stem_s2d, x, 64)
    print(f"stem fwd: xla7x7 {t0*1e3:.3f}ms {flops/t0/1e12:.1f}TF | "
          f"s2d {t1*1e3:.3f}ms {flops/t1/1e12:.1f}TF  err={err:.0e}",
          flush=True)

    def train_ref(c):
        return jax.grad(lambda xx: jnp.sum(jax.nn.relu(
            conv(xx, w, 2)).astype(jnp.float32)))(c)

    def train_s2d(c):
        def f(xx):
            xs = space_to_depth(xx, 2)
            xs = jnp.pad(xs, ((0, 0), (2, 1), (2, 1), (0, 0)))
            return jnp.sum(jax.nn.relu(
                conv(xs, w4, 1, "VALID")).astype(jnp.float32))
        return jax.grad(f)(c)
    t0 = time_chain(train_ref, x, 64)
    t1 = time_chain(train_s2d, x, 64)
    print(f"stem f+d: xla7x7 {t0*1e3:.3f}ms | s2d {t1*1e3:.3f}ms", flush=True)

    # ---------------- 2. strided 1x1 projection --------------------
    x = jnp.asarray(rng.standard_normal((N, 56, 56, 256)) * 0.1, jnp.bfloat16)
    wp = jnp.asarray(rng.standard_normal((1, 1, 256, 512)) * 0.1, jnp.bfloat16)
    wb = jnp.asarray(rng.standard_normal((1, 1, 512, 256)) * 0.1, jnp.bfloat16)
    flops = 2 * N * 28 * 28 * 256 * 512

    def proj_ref(c):
        y = jax.nn.relu(conv(c, wp, 2))
        y = conv(y, wb)
        return up2(y, 56)

    def proj_slice(c):
        y = jax.nn.relu(conv(c[:, ::2, ::2, :], wp, 1))
        y = conv(y, wb)
        return up2(y, 56)

    t0 = time_chain(proj_ref, x, 96)
    t1 = time_chain(proj_slice, x, 96)
    print(f"proj1x1s2 fwd: conv-s2 {t0*1e3:.3f}ms {flops/t0/1e12:.1f}TF | "
          f"slice+mm {t1*1e3:.3f}ms {flops/t1/1e12:.1f}TF", flush=True)

    # ---------------- 3. Pallas wgrad GEMM for 1x1 -----------------
    H = W = 56
    Cs, Cl = 64, 256
    R = N * H * W                         # 401408 reduction rows
    x1 = jnp.asarray(rng.standard_normal((R, Cs)) * 0.1, jnp.bfloat16)
    g1 = jnp.asarray(rng.standard_normal((R, Cl)) * 0.1, jnp.bfloat16)
    flops = 2 * R * Cs * Cl

    def wgrad_xla(g):
        return jax.lax.dot_general(
            x1, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)

    TR = 4096

    def wgrad_kernel(x_ref, g_ref, o_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)
        o_ref[:] += jax.lax.dot_general(
            x_ref[:], g_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def wgrad_pl(g):
        out = pl.pallas_call(
            wgrad_kernel,
            grid=(R // TR,),
            in_specs=[pl.BlockSpec((TR, Cs), lambda i: (i, 0)),
                      pl.BlockSpec((TR, Cl), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((Cs, Cl), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((Cs, Cl), jnp.float32),
        )(x1, g)
        return out.astype(jnp.bfloat16)

    ref = np.asarray(wgrad_xla(g1), np.float32)
    got = np.asarray(wgrad_pl(g1), np.float32)
    err = np.max(np.abs(got - ref)) / np.max(np.abs(ref))

    # chain over g's first Cs columns -> keep carry g-shaped: wrap
    def chain_xla(g):
        dw = wgrad_xla(g)                 # (Cs, Cl)
        return g + jnp.tile(dw, (R // Cs, 1)).astype(g.dtype) * 0

    # simpler honest chain: carry (Cs, Cl) seed mixed into g each step
    seed = jnp.zeros((Cs, Cl), jnp.bfloat16)

    def mk_chain(wgrad):
        def step(c):
            gg = g1 * (1 + c[0, 0])
            return wgrad(gg).astype(jnp.bfloat16)
        return step
    t0 = time_chain(mk_chain(wgrad_xla), seed, 128)
    t1 = time_chain(mk_chain(wgrad_pl), seed, 128)
    print(f"1x1 wgrad 56 64x256: xla {t0*1e3:.3f}ms {flops/t0/1e12:.1f}TF | "
          f"pallas {t1*1e3:.3f}ms {flops/t1/1e12:.1f}TF  err={err:.0e}",
          flush=True)


if __name__ == "__main__":
    main()
