#!/usr/bin/env python
"""Pack an image dataset into RecordIO (.rec + .idx + .lst).

Reference analog: ``tools/im2rec.py`` / ``tools/im2rec.cc`` (SURVEY.md N24):
builds the packed input format consumed by ImageRecordIter.  Uses the native
RecordIO writer (src/recordio.cc) and OpenCV JPEG encoding.

Usage:
  python tools/im2rec.py --list prefix image_root      # make prefix.lst
  python tools/im2rec.py prefix image_root             # pack prefix.rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root, recursive=True, train_ratio=1.0, shuffle=True,
              chunks=1):
    """Write prefix.lst: ``index \\t label \\t relpath`` per image; labels
    are per-subdirectory class ids (reference im2rec.py --list)."""
    entries = []
    classes = {}
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        rel = os.path.relpath(dirpath, root)
        for fname in sorted(filenames):
            if not fname.lower().endswith(EXTS):
                continue
            # loose root images form their own class like any directory
            label = classes.setdefault(rel, len(classes))
            entries.append((label, os.path.join(rel, fname)
                            if rel != "." else fname))
        if not recursive:
            break
    if shuffle:
        random.shuffle(entries)
    n_train = int(len(entries) * train_ratio)
    splits = [("", entries[:n_train])]
    if train_ratio < 1.0:
        splits = [("_train", entries[:n_train]), ("_val", entries[n_train:])]
    for suffix, rows in splits:
        with open(prefix + suffix + ".lst", "w") as f:
            for i, (label, path) in enumerate(rows):
                f.write("%d\t%f\t%s\n" % (i, float(label), path))
    return classes


def read_list(lst_path):
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, lst_path=None, quality=95, resize=0,
         color=1, encoding=".jpg"):
    """Pack images listed in prefix.lst into prefix.rec/.idx
    (reference im2rec.py packing loop)."""
    import cv2
    import numpy as np
    lst_path = lst_path or prefix + ".lst"
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, relpath in read_list(lst_path):
        path = os.path.join(root, relpath)
        flag = cv2.IMREAD_COLOR if color else cv2.IMREAD_GRAYSCALE
        img = cv2.imread(path, flag)
        if img is None:
            print("skip unreadable image:", path, file=sys.stderr)
            continue
        if resize:
            h, w = img.shape[:2]
            if h > w:
                img = cv2.resize(img, (resize, int(h * resize / w)))
            else:
                img = cv2.resize(img, (int(w * resize / h), resize))
        ok, buf = cv2.imencode(encoding, img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        if not ok:
            print("skip unencodable image:", path, file=sys.stderr)
            continue
        label = labels[0] if len(labels) == 1 else np.asarray(labels,
                                                              np.float32)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, buf.tobytes()))
        count += 1
    rec.close()
    return count


def main(argv=None):
    ap = argparse.ArgumentParser(description="image dataset -> RecordIO")
    ap.add_argument("prefix", help="output prefix (prefix.rec/.idx/.lst)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="only generate the .lst file")
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter side to this many pixels")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--color", type=int, default=1, choices=[0, 1])
    args = ap.parse_args(argv)
    if args.list:
        classes = make_list(args.prefix, args.root,
                            shuffle=not args.no_shuffle,
                            train_ratio=args.train_ratio)
        print("wrote %s.lst (%d classes)" % (args.prefix, len(classes)))
        return 0
    # pack every list matching the prefix: prefix.lst, or the
    # prefix_train.lst/prefix_val.lst pair from --list --train-ratio
    lsts = [suf for suf in ("", "_train", "_val")
            if os.path.exists(args.prefix + suf + ".lst")]
    if not lsts:
        make_list(args.prefix, args.root, shuffle=not args.no_shuffle)
        lsts = [""]
    for suf in lsts:
        n = pack(args.prefix + suf, args.root,
                 lst_path=args.prefix + suf + ".lst", resize=args.resize,
                 quality=args.quality, color=args.color)
        print("packed %d records into %s.rec" % (n, args.prefix + suf))
    return 0


if __name__ == "__main__":
    sys.exit(main())
