#!/usr/bin/env python
"""Probe: streaming-CE buffer footprint of the PUBLIC gluon loss on TPU.

Round-3 verdict item 2 evidence: compiles gluon.loss.SoftmaxCrossEntropyLoss
(forward and gradient) at the LM bench shape (T*B=2560, vocab=33278, bf16)
on the current default backend and prints the XLA temp-allocation size.
On TPU both compile to temp=0 B — the logsumexp/convert/exp chain fuses
entirely into the reductions, so no (N, vocab) buffer of ANY dtype is
allocated (measured 2026-07-31 on v5e via the axon tunnel; the CPU backend
instead materializes one converted operand for its reduce-window strategy,
which is why tests/test_streaming_ce.py asserts the relative-footprint
form on CPU and the strict form on TPU).
"""
import jax
import jax.numpy as jnp

from mxnet_tpu import gluon
from mxnet_tpu.ndarray.ndarray import NDArray

BIG = (2560, 33278)
F32_BUF = BIG[0] * BIG[1] * 4


def public_mean_ce(lg, lab):
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    return jnp.mean(ce(NDArray(lg), NDArray(lab))._data
                    .astype(jnp.float32))


def main():
    print("backend:", jax.default_backend())
    lg = jax.ShapeDtypeStruct(BIG, jnp.bfloat16)
    lab = jax.ShapeDtypeStruct((BIG[0],), jnp.float32)
    for name, fn in (("forward", public_mean_ce),
                     ("gradient", jax.grad(public_mean_ce))):
        ma = jax.jit(fn).lower(lg, lab).compile().memory_analysis()
        print("%s: temp=%.2f MB (f32 (N,vocab) buffer would be %.1f MB) %s"
              % (name, ma.temp_size_in_bytes / 1e6, F32_BUF / 1e6,
                 "OK" if ma.temp_size_in_bytes < F32_BUF else "FAIL"))


if __name__ == "__main__":
    main()
