#!/usr/bin/env python
"""Program Atlas CLI: per-layer flop/byte attribution of fused XLA programs.

Front-end for :mod:`mxnet_tpu.atlas` (see docs/observability.md "Atlas").
Modes:

- ``A.json`` (positional) — render a saved atlas snapshot (the /programz
  ``atlas`` block, ``bench.py --atlas`` output, or a flight-recorder
  dump's ``atlas`` block) as a ranked table or JSON.
- ``--url http://host:port`` — fetch ``/programz`` from a live telemetry
  server and render its atlas block.
- ``--diff A.json B.json`` — per-scope flop/byte deltas between two
  snapshots: the before/after attribution of a perf change.
- ``--smoke`` — self-contained acceptance check: train a ResNet-50-style
  fused Module step (CPU shapes), then assert (a) the step program's
  atlas attributes >= 90% of its ``cost_analysis()`` flops to named
  scopes and (b) the analysis added ZERO XLA compiles (jit-cache miss
  counters are flat across a second step).

``--format json`` always emits the snapshot (or diff rows) as JSON, so
``--smoke --format json > A.json`` feeds ``--diff`` later.

Run:  python -m tools.program_atlas [snapshot.json] [--top-k N]
      [--format table|json] [--diff A.json B.json] [--url URL] [--smoke]
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt_flops(f):
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(f) >= div:
            return "%.2f%s" % (f / div, unit)
    return "%.0f" % f


def _fmt_bytes(b):
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(b) >= div:
            return "%.2f%s" % (b / div, unit)
    return "%dB" % int(b)


def render_snapshot(snap, top_k, out=None):
    """Human table of a {program: atlas-dict} snapshot."""
    out = out if out is not None else sys.stdout
    if not snap:
        print("(no analyzed programs)", file=out)
        return
    for name, doc in sorted(snap.items()):
        print("program %s  flops=%s  coverage=%.1f%%  scopes=%d  "
              "instructions=%d"
              % (name, _fmt_flops(doc.get("total_flops", 0.0)),
                 doc.get("coverage_pct", 0.0), doc.get("n_scopes", 0),
                 doc.get("n_instructions", 0)), file=out)
        rows = doc.get("scopes", [])[:top_k] if top_k else doc.get("scopes", [])
        if not rows:
            print("  (no scoped instructions)", file=out)
            continue
        w = max(len(r["scope"]) for r in rows)
        print("  %-*s %10s %7s %10s %7s %6s" % (
            w, "scope", "flops", "f%", "bytes", "b%", "instrs"), file=out)
        for r in rows:
            print("  %-*s %10s %6.1f%% %10s %6.1f%% %6d" % (
                w, r["scope"], _fmt_flops(r["flops"]),
                100.0 * r.get("flops_share", 0.0), _fmt_bytes(r["bytes"]),
                100.0 * r.get("bytes_share", 0.0), r["instructions"]),
                file=out)


def render_diff(rows, top_k, out=None):
    out = out if out is not None else sys.stdout
    if not rows:
        print("(no per-scope deltas)", file=out)
        return
    rows = rows[:top_k] if top_k else rows
    w = max(len("%s/%s" % (r["program"], r["scope"])) for r in rows)
    print("%-*s %12s %12s %12s %12s" % (
        w, "program/scope", "flops A", "flops B", "d flops", "d bytes"),
        file=out)
    for r in rows:
        print("%-*s %12s %12s %+12s %+12s" % (
            w, "%s/%s" % (r["program"], r["scope"]),
            _fmt_flops(r["flops_a"]), _fmt_flops(r["flops_b"]),
            _fmt_flops(r["delta_flops"]), _fmt_bytes(r["delta_bytes"])),
            file=out)


def _load_snapshot(path):
    """Accept a bare atlas snapshot, a /programz doc, or a flight dump."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "atlas" in doc \
            and isinstance(doc["atlas"], dict):
        return doc["atlas"]
    return doc


def _fetch_programz(url):
    from urllib.request import urlopen
    if not url.rstrip("/").endswith("/programz"):
        url = url.rstrip("/") + "/programz"
    with urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _counter_total(name):
    """Sum of one counter family over every label combination."""
    from mxnet_tpu import telemetry
    fam = telemetry.registry().get(name)
    if fam is None:
        return 0.0
    return sum(data for _, data in fam.samples())


def smoke(fmt, top_k):
    """ResNet-50-style fused Module step -> coverage + zero-compile gates."""
    os.environ.setdefault("MXNET_HEALTH", "1")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import atlas, health, telemetry
    from mxnet_tpu.gluon.model_zoo import vision

    telemetry.enable()
    health.enable()

    batch, image = 2, 32          # CPU-sized ResNet-50 v1 step
    net = vision.resnet50_v1()
    out = net(mx.sym.var("data"))
    sym = mx.sym.SoftmaxOutput(out, mx.sym.var("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",),
                        context=[mx.cpu()])
    mod.bind(data_shapes=[("data", (batch, 3, image, image))],
             label_shapes=[("softmax_label", (batch,))])
    mx.random.seed(7)
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    rs = np.random.RandomState(3)
    x = mx.nd.array(rs.uniform(size=(batch, 3, image, image))
                    .astype(np.float32))
    y = mx.nd.array(rs.randint(0, 1000, (batch,)).astype(np.float32))

    class _B:
        data = [x]
        label = [y]

    def step():
        mod.forward_backward(_B)
        mod.update()
        mod.get_outputs()[0].asnumpy()

    step()  # first step: compile + health registration + atlas analysis

    prog = None
    for name in ("mesh_step", "step"):
        if atlas.get(name) is not None:
            prog = name
            break
    ok = True
    if prog is None:
        print("SMOKE FAIL: no step program analyzed "
              "(registered: %s, atlases: %s)"
              % (sorted(health.programs()), sorted(atlas.atlases())),
              file=sys.stderr)
        ok = False
    else:
        atl = atlas.get(prog)
        cov = atl.coverage()
        if cov < 0.90:
            print("SMOKE FAIL: %s coverage %.1f%% < 90%%"
                  % (prog, 100.0 * cov), file=sys.stderr)
            ok = False
        # zero-extra-compile gate: a second identical step must be all
        # cache hits — flat miss counters prove the lowering-only
        # analysis (health + atlas) triggered no recompilation
        misses0 = _counter_total("op_jit_cache_misses_total")
        step()
        misses1 = _counter_total("op_jit_cache_misses_total")
        if misses1 != misses0:
            print("SMOKE FAIL: jit-cache misses moved %s -> %s across a "
                  "repeat step (unexpected recompiles)"
                  % (misses0, misses1), file=sys.stderr)
            ok = False

    snap = atlas.snapshot(top_k=top_k)
    if fmt == "json":
        json.dump(snap, sys.stdout, indent=2)
        print()
    else:
        render_snapshot(snap, top_k)
        if ok and prog is not None:
            print("SMOKE OK: %s coverage %.1f%%, zero extra compiles"
                  % (prog, 100.0 * atlas.get(prog).coverage()))
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="program_atlas",
        description="per-layer flop/byte attribution of fused XLA programs")
    ap.add_argument("snapshot", nargs="?",
                    help="saved atlas snapshot / /programz doc / flight "
                         "dump to render")
    ap.add_argument("--top-k", type=int, default=10,
                    help="rows per program (0 = all)")
    ap.add_argument("--format", choices=("table", "json"), default="table")
    ap.add_argument("--diff", nargs=2, metavar=("A.json", "B.json"),
                    help="rank per-scope deltas between two snapshots")
    ap.add_argument("--url", help="fetch /programz from a live server")
    ap.add_argument("--smoke", action="store_true",
                    help="self-contained coverage + zero-compile check")
    args = ap.parse_args(argv)
    top_k = args.top_k or None

    if args.smoke:
        return smoke(args.format, top_k)

    if args.diff:
        from mxnet_tpu import atlas
        rows = atlas.diff(_load_snapshot(args.diff[0]),
                          _load_snapshot(args.diff[1]))
        if args.format == "json":
            json.dump(rows, sys.stdout, indent=2)
            print()
        else:
            render_diff(rows, top_k)
        return 0

    if args.url:
        doc = _fetch_programz(args.url)
        snap = doc.get("atlas", {})
        if args.format == "json":
            json.dump(doc, sys.stdout, indent=2)
            print()
        else:
            render_snapshot(snap, top_k)
        return 0

    if args.snapshot:
        snap = _load_snapshot(args.snapshot)
        if args.format == "json":
            json.dump(snap, sys.stdout, indent=2)
            print()
        else:
            render_snapshot(snap, top_k)
        return 0

    ap.error("nothing to do: pass a snapshot file, --url, --diff or --smoke")


if __name__ == "__main__":
    sys.exit(main())
