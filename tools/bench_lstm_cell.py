#!/usr/bin/env python
"""Microbench: Pallas fused LSTM recurrence vs lax.scan (fwd+bwd).

Reproduces the docs/perf_analysis.md round-3 number (isolated recurrence
at the LM shape T=35 B=128 H=650: scan 0.405 ms -> pallas 0.319 ms,
+21%).  Differential chained timing cancels the tunnel RTT.

Run on TPU:  python tools/bench_lstm_cell.py [T B H]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from mxnet_tpu.ops import pallas_rnn

REPS = 4
CHAIN = 100


def time_chain(step, x0):
    def build(n):
        @jax.jit
        def f(x):
            def body(c, _):
                return step(c) * jnp.bfloat16(0.25), None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(y.astype(jnp.float32))
        return f
    f1, f2 = build(CHAIN), build(2 * CHAIN)
    float(f1(x0)); float(f2(x0))
    best1 = best2 = 1e9
    for _ in range(REPS):
        t0 = time.perf_counter(); float(f1(x0))
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter(); float(f2(x0))
        best2 = min(best2, time.perf_counter() - t0)
    return max(best2 - best1, 1e-9) / CHAIN


def main():
    T, B, H = (int(a) for a in sys.argv[1:4]) if len(sys.argv) > 3 \
        else (35, 128, 650)
    rng = np.random.default_rng(0)
    xproj = jnp.asarray(rng.standard_normal((T, B, 4 * H)) * 0.1,
                        jnp.bfloat16)
    h0 = jnp.zeros((B, H), jnp.bfloat16)
    c0 = jnp.zeros((B, H), jnp.bfloat16)
    R = jnp.asarray(rng.standard_normal((4 * H, H)) * 0.1, jnp.bfloat16)
    bR = jnp.asarray(rng.standard_normal((4 * H,)) * 0.1, jnp.bfloat16)

    def scan_ref(xp):
        def step(carry, x):
            h, c = carry
            g = x + h @ R.T + bR
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gg)
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            return (h2, c2), h2
        _, ys = jax.lax.scan(step, (h0, c0), xp)
        return ys

    def pallas_fn(xp):
        ys, _, _ = pallas_rnn.lstm_scan(xp, h0, c0, R, bR)
        return ys

    for name, f in [("lax.scan", scan_ref), ("pallas", pallas_fn)]:
        def fwdbwd(c, f=f):
            return jax.grad(
                lambda xp: jnp.sum(f(xp).astype(jnp.float32) ** 2))(c)
        t = time_chain(fwdbwd, xproj)
        print(f"{name:9} recurrence fwd+bwd (T={T},B={B},H={H}): "
              f"{t*1e3:.3f} ms/window")


if __name__ == "__main__":
    main()
