#!/usr/bin/env python
"""Distributed job launcher (parity: tools/launch.py + dmlc_tracker local).

Reference analog: ``tools/launch.py:29-50`` — starts a scheduler, S servers
and W workers via dmlc_tracker (ssh/mpi/local).  TPU-native: there is no
parameter server; this launcher starts W worker processes wired to one JAX
distributed coordinator (rank 0).  The reference's env contract is kept so
``launch.py -n 4 python train.py --kv-store dist_sync`` works unchanged:

  DMLC_ROLE=worker  DMLC_NUM_WORKER=W  DMLC_WORKER_ID=rank
  DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT -> the JAX coordinator address

``-s`` (server count) is accepted and ignored with a note: dist_sync rides
XLA collectives over DCN, not ps-lite (SURVEY.md §5.8).
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers: int, command, env_extra=None) -> int:
    """Fork ``num_workers`` local processes (the dmlc_tracker 'local'
    backend pattern of tests/nightly/test_all.sh:55)."""
    port = _free_port()
    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(num_workers),
            "DMLC_WORKER_ID": str(rank),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        procs.append(subprocess.Popen(command, env=env))
    # poll rather than wait serially: when one rank dies the others may be
    # blocked in the coordinator rendezvous forever — kill them fast
    import time
    rc = 0
    alive = list(procs)
    while alive:
        time.sleep(0.2)
        for p in list(alive):
            code = p.poll()
            if code is None:
                continue
            alive.remove(p)
            if code != 0 and rc == 0:
                rc = code
                for q in alive:
                    q.terminate()
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference compatibility; ignored "
                         "(no parameter server on the TPU backend)")
    ap.add_argument("--launcher", choices=["local"], default="local",
                    help="only the local (single-host fork) tracker is "
                         "built in; multi-host uses the cluster scheduler's "
                         "own launcher + JAX coordinator env")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="the training command to run on every worker")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    if args.num_servers:
        print("note: -s/--num-servers ignored — dist kvstore uses XLA "
              "collectives, not parameter servers", file=sys.stderr)
    return launch_local(args.num_workers, args.command)


if __name__ == "__main__":
    sys.exit(main())
