#!/usr/bin/env python
"""Distributed job launcher (parity: tools/launch.py + dmlc_tracker local).

Reference analog: ``tools/launch.py:29-50`` — starts a scheduler, S servers
and W workers via dmlc_tracker (ssh/mpi/local).  TPU-native: this launcher
starts W worker processes wired to one JAX distributed coordinator
(rank 0), plus — with ``-s`` — one parameter-server process for
``dist_async`` (mxnet_tpu.kvstore_server).  The reference's env contract
is kept so ``launch.py -n 4 python train.py --kv-store dist_sync`` works
unchanged:

  DMLC_ROLE=worker|server  DMLC_NUM_WORKER=W  DMLC_WORKER_ID=rank
  DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT -> the JAX coordinator address
  MXNET_PS_URI / MXNET_PS_PORT         -> the dist_async parameter server

``dist_sync`` rides XLA collectives over DCN, not ps-lite (SURVEY.md
§5.8); the server role exists for the async-SGD semantics only.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers: int, command, env_extra=None,
                 num_servers: int = 0) -> int:
    """Fork ``num_workers`` local processes (the dmlc_tracker 'local'
    backend pattern of tests/nightly/test_all.sh:55).  With
    ``num_servers`` > 0 one extra process runs the same command with
    ``DMLC_ROLE=server`` — it enters the parameter-server loop inside
    ``kvstore.create('dist_async')`` (reference behavior: the training
    script doubles as the server binary)."""
    port = _free_port()
    ps_port = _free_port() if num_servers else None
    procs = []
    base = {
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
    }
    if ps_port is not None:
        base["MXNET_PS_URI"] = "127.0.0.1"
        base["MXNET_PS_PORT"] = str(ps_port)
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update(base)
        env["DMLC_ROLE"] = "server"
        procs.append(subprocess.Popen(command, env=env))
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update(base)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_WORKER_ID": str(rank),
        })
        procs.append(subprocess.Popen(command, env=env))
    # poll rather than wait serially: when one rank dies the others may be
    # blocked in the coordinator rendezvous forever — kill them fast
    import time
    rc = 0
    server = procs[0] if ps_port is not None else None
    workers = procs[1:] if ps_port is not None else procs
    alive = list(workers)
    while alive:
        time.sleep(0.2)
        if server is not None and server.poll() not in (None, 0) and rc == 0:
            rc = server.poll()          # server crashed: tear down the job
            for q in alive:
                q.terminate()
        for p in list(alive):
            code = p.poll()
            if code is None:
                continue
            alive.remove(p)
            if code != 0 and rc == 0:
                rc = code
                for q in alive:
                    q.terminate()
    if server is not None:
        # workers are done; the server idles until stopped (reference:
        # rank 0 sends kStopServer) — reap it either way
        time.sleep(0.2)
        if server.poll() is None:
            server.terminate()
        server.wait()
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="start a parameter server for dist_async (>0 "
                         "starts one; dist_sync needs none — it rides XLA "
                         "collectives)")
    ap.add_argument("--launcher", choices=["local"], default="local",
                    help="only the local (single-host fork) tracker is "
                         "built in; multi-host uses the cluster scheduler's "
                         "own launcher + JAX coordinator env")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="the training command to run on every worker")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    if args.num_servers > 1:
        print("note: one parameter server is started (the single-server "
              "case of the reference's -s)", file=sys.stderr)
    return launch_local(args.num_workers, args.command,
                        num_servers=args.num_servers)


if __name__ == "__main__":
    sys.exit(main())
