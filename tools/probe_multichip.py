#!/usr/bin/env python
"""Probe: run ``bench.py --multichip`` and validate the emitted JSON.

``--smoke`` uses the tiny MLP model so the probe finishes in ~1 min on a
dev box (virtual CPU devices); without it the real resnet50 workload runs.
Asserts the record carries the multichip contract keys — the driver and
docs/perf_analysis.md both key on ``img_per_sec`` and
``scaling_efficiency`` — and that the mesh-fused path actually dispatched.

Usage:
    python tools/probe_multichip.py --smoke
    python tools/probe_multichip.py            # full resnet50 bench
"""
import json
import os
import subprocess
import sys
import tempfile

REQUIRED_KEYS = ("metric", "img_per_sec", "scaling_efficiency",
                 "n_devices", "mesh_fused_steps", "ok")


def main(argv):
    smoke = "--smoke" in argv
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tempfile.NamedTemporaryFile(
        suffix=".json", prefix="multichip_", delete=False)
    out.close()
    env = dict(os.environ)
    env["MULTICHIP_OUT"] = out.name
    if smoke:
        env["BENCH_MULTICHIP_MODEL"] = "mlp"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--multichip"],
        env=env, cwd=repo, capture_output=True, text=True,
        timeout=600 if smoke else 3000)
    if proc.returncode != 0:
        print("bench --multichip failed (rc=%d)\n--- stdout ---\n%s\n"
              "--- stderr ---\n%s" % (proc.returncode,
                                      proc.stdout[-4000:],
                                      proc.stderr[-4000:]))
        return proc.returncode
    with open(out.name) as f:
        rec = json.load(f)
    os.unlink(out.name)

    missing = [k for k in REQUIRED_KEYS if k not in rec]
    assert not missing, "multichip record missing keys %s: %r" \
        % (missing, rec)
    assert rec["img_per_sec"] > 0, rec
    assert 0 < rec["scaling_efficiency"], rec
    assert rec["mesh_fused_steps"] > 0, \
        "mesh-fused path never dispatched: %r" % rec
    assert rec["ok"] is True, rec
    print(json.dumps({"probe": "multichip", "smoke": smoke, "ok": True,
                      "metric": rec["metric"],
                      "img_per_sec": rec["img_per_sec"],
                      "scaling_efficiency": rec["scaling_efficiency"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
