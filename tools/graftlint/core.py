"""graftlint core: project model, shared AST cache, call graph, findings.

The analyzer parses every file once into a :class:`Project` and shares the
ASTs (plus per-function fact caches) across all checks — that is what keeps
the tier-1 run under the 10 s budget.  Resolution is deliberately
conservative: a call we cannot resolve statically is skipped, never
guessed, so every finding corresponds to a concrete code path.
"""
from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "ModuleInfo", "Project", "Suppression",
    "load_baseline", "save_baseline", "split_by_baseline",
]

# inline suppression:  # graftlint: disable=GL001[,GL002] -- reason
SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s+--\s*(\S.*?))?\s*$")

_BUMP_ATTRS = ("inc", "dec", "set", "observe")
_INSTRUMENT_CTORS = ("counter", "gauge", "histogram")

# jax host-callback APIs: functions handed to these run on the HOST per
# call, not at trace time — reachability walks must not cross into them
_HOST_CALLBACKS = ("io_callback", "pure_callback", "callback",
                   "debug_callback")


@dataclass(frozen=True)
class Finding:
    code: str           # "GL001" .. "GL005", "GL000" for bad suppressions
    path: str           # repo-relative posix path
    line: int
    message: str
    detail: str         # stable (line-free) identity used for baselining

    @property
    def fingerprint(self) -> str:
        return "%s|%s|%s" % (self.code, self.path, self.detail)

    def as_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "path": self.path, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint}


@dataclass
class Suppression:
    path: str
    line: int
    codes: Set[str]
    reason: Optional[str]


class _Scope:
    """Static scope info attached to every function/lambda node."""

    __slots__ = ("mod", "cls", "qual", "locals", "owner")

    def __init__(self, mod, cls, qual, owner):
        self.mod = mod          # ModuleInfo
        self.cls = cls          # enclosing class name or None
        self.qual = qual        # dotted qualname within the module
        self.locals = {}        # name -> nested FunctionDef
        self.owner = owner      # enclosing function node or None


@dataclass
class CallSite:
    node: ast.AST
    line: int
    chain: Optional[Tuple[str, ...]]   # dotted name parts, None if dynamic
    canon: Optional[str]               # canonical external name if importable
    targets: List[ast.AST]             # resolved in-project function nodes
    is_ref: bool = False               # function passed as an argument


@dataclass
class EnvRead:
    key: Optional[str]                 # None = dynamic (non-literal) key
    line: int


@dataclass
class Bump:
    instrument: str                    # module-global instrument name
    metric: Optional[str]              # metric name literal if known
    line: int


@dataclass
class FunctionFacts:
    calls: List[CallSite] = field(default_factory=list)
    env_reads: List[EnvRead] = field(default_factory=list)
    bumps: List[Bump] = field(default_factory=list)


class ModuleInfo:
    def __init__(self, path: Path, rel: str, name: str, tree: ast.Module,
                 source: str):
        self.path = path
        self.rel = rel
        self.name = name
        self.tree = tree
        self.lines = source.splitlines()
        if path.name == "__init__.py":
            self.package = name
        else:
            self.package = name.rsplit(".", 1)[0] if "." in name else ""
        self.functions: Dict[str, ast.AST] = {}      # qual -> def node
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_bases: Dict[str, List[str]] = {}
        self.imports: Dict[str, str] = {}            # alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.consts: Dict[str, Any] = {}             # module-level literals
        self.class_consts: Dict[Tuple[str, str], Any] = {}
        # module globals assigned from telemetry counter/gauge/histogram()
        self.instruments: Dict[str, Tuple[str, Optional[str], int]] = {}
        self._suppressions: Optional[Dict[int, Suppression]] = None

    # -- suppressions -----------------------------------------------------
    def suppressions(self) -> Dict[int, Suppression]:
        if self._suppressions is None:
            out = {}
            for i, text in enumerate(self.lines, start=1):
                m = SUPPRESS_RE.search(text)
                if not m:
                    continue
                codes = {c.strip().upper()
                         for c in m.group(1).split(",") if c.strip()}
                out[i] = Suppression(self.rel, i, codes, m.group(2))
            self._suppressions = out
        return self._suppressions

    def suppression_for(self, line: int, code: str) -> Optional[Suppression]:
        sup = self.suppressions()
        for cand in (line, line - 1):
            s = sup.get(cand)
            if s is None or code not in s.codes:
                continue
            if cand == line:
                return s
            # directive on the previous line counts only if that line is
            # a pure comment (a trailing directive binds to its own line)
            text = self.lines[cand - 1].strip()
            if text.startswith("#"):
                return s
        return None


def _dotted(node) -> Optional[Tuple[str, ...]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _literal_strings(node) -> Optional[Tuple[str, ...]]:
    """Tuple/list of string constants, else None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.append(elt.value)
            else:
                return None
        return tuple(vals)
    return None


class Project:
    """Parsed view of one or more packages under a root directory."""

    def __init__(self, root,
                 packages: Sequence[str] = ("mxnet_tpu", "tools", "bench"),
                 config: Optional[Dict[str, Any]] = None):
        self.root = Path(root)
        self.packages = tuple(packages)
        self.config: Dict[str, Any] = dict(config or {})
        self.modules: Dict[str, ModuleInfo] = {}
        self.parse_errors: List[Finding] = []
        self._facts: Dict[int, FunctionFacts] = {}
        self._load()

    # -- loading / indexing ----------------------------------------------
    def _load(self) -> None:
        for pkg in self.packages:
            base = self.root / pkg.replace(".", "/")
            if base.is_dir():
                paths = sorted(base.rglob("*.py"))
            elif base.with_suffix(".py").is_file():
                # a package entry may be a single top-level module
                # (bench.py lives at the repo root, not in a package)
                paths = [base.with_suffix(".py")]
            else:
                paths = []
            for path in paths:
                rel = path.relative_to(self.root).as_posix()
                stem = rel[:-3].replace("/", ".")
                name = stem[:-len(".__init__")] \
                    if stem.endswith(".__init__") else stem
                try:
                    source = path.read_text(encoding="utf-8")
                    tree = ast.parse(source, filename=rel)
                except (SyntaxError, UnicodeDecodeError) as exc:
                    self.parse_errors.append(Finding(
                        "GL000", rel, getattr(exc, "lineno", 1) or 1,
                        "file does not parse: %s" % exc, "parse-error"))
                    continue
                mod = ModuleInfo(path, rel, name, tree, source)
                self.modules[name] = mod
                self._index(mod)

    def _index(self, mod: ModuleInfo) -> None:
        def add_import(node):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
                    else:
                        head = alias.name.split(".")[0]
                        mod.imports[head] = head
            elif isinstance(node, ast.ImportFrom):
                src = node.module or ""
                if node.level:
                    parts = mod.package.split(".") if mod.package else []
                    if node.level > 1:
                        parts = parts[:len(parts) - (node.level - 1)]
                    if src:
                        parts = parts + src.split(".")
                    src = ".".join(parts)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mod.from_imports[alias.asname or alias.name] = \
                        (src, alias.name)

        def record_const(target, value, cls):
            if not isinstance(target, ast.Name):
                return
            lit: Any = None
            if isinstance(value, ast.Constant) and \
                    isinstance(value.value, str):
                lit = value.value
            else:
                lit = _literal_strings(value)
            if lit is None:
                return
            if cls is None:
                mod.consts[target.id] = lit
            else:
                mod.class_consts[(cls, target.id)] = lit

        def record_instrument(target, value):
            if not (isinstance(target, ast.Name) and
                    isinstance(value, ast.Call)):
                return
            chain = _dotted(value.func)
            if not chain or chain[-1] not in _INSTRUMENT_CTORS:
                return
            base_ok = len(chain) == 1 or "telemetry" in chain[0].lower()
            if not base_ok:
                canon = self.canonical(mod, chain)
                base_ok = bool(canon) and "telemetry" in canon
            if not base_ok:
                return
            metric = None
            if value.args and isinstance(value.args[0], ast.Constant) \
                    and isinstance(value.args[0].value, str):
                metric = value.args[0].value
            mod.instruments[target.id] = \
                (chain[-1], metric, value.lineno)

        def rec(node, cls, qual_parts, owner):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    add_import(child)
                    continue
                if isinstance(child,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join(qual_parts + [child.name])
                    child._gl = _Scope(mod, cls, qual, owner)  # type: ignore
                    mod.functions[qual] = child
                    if owner is not None:
                        owner._gl.locals[child.name] = child
                    rec(child, cls, qual_parts + [child.name], child)
                elif isinstance(child, ast.Lambda):
                    qual = ".".join(qual_parts + ["<lambda>"])
                    child._gl = _Scope(mod, cls, qual, owner)  # type: ignore
                    rec(child, cls, qual_parts, owner)
                elif isinstance(child, ast.ClassDef):
                    mod.classes[child.name] = child
                    bases = []
                    for base in child.bases:
                        d = _dotted(base)
                        if d:
                            bases.append(".".join(d))
                    mod.class_bases[child.name] = bases
                    rec(child, child.name, [child.name], None)
                else:
                    if isinstance(child, ast.Assign) and owner is None:
                        for tgt in child.targets:
                            record_const(tgt, child.value, cls)
                            if cls is None:
                                record_instrument(tgt, child.value)
                    rec(child, cls, qual_parts, owner)

        rec(mod.tree, None, [], None)

    # -- name resolution --------------------------------------------------
    def canonical(self, mod: ModuleInfo,
                  chain: Optional[Tuple[str, ...]]) -> Optional[str]:
        """Absolute dotted name for an imported chain ('jax.jit',
        'os.environ.get'), or None for local/unresolvable names."""
        if not chain:
            return None
        head = chain[0]
        if head in mod.imports:
            return ".".join((mod.imports[head],) + chain[1:])
        if head in mod.from_imports:
            src, attr = mod.from_imports[head]
            base = src + "." + attr if src else attr
            return ".".join((base,) + chain[1:])
        return None

    def _lookup_method(self, mod: ModuleInfo, cls: str,
                       attr: str, depth: int = 0) -> Optional[ast.AST]:
        fn = mod.functions.get(cls + "." + attr)
        if fn is not None:
            return fn
        if depth >= 2:
            return None
        for base in mod.class_bases.get(cls, ()):
            parts = base.split(".")
            if len(parts) == 1:
                if parts[0] in mod.classes:
                    got = self._lookup_method(mod, parts[0], attr, depth + 1)
                    if got is not None:
                        return got
                elif parts[0] in mod.from_imports:
                    src, name = mod.from_imports[parts[0]]
                    bmod = self.modules.get(src)
                    if bmod is not None and name in bmod.classes:
                        got = self._lookup_method(bmod, name, attr, depth + 1)
                        if got is not None:
                            return got
        return None

    def _module_attr(self, modname: str, attr: str) -> Optional[ast.AST]:
        tm = self.modules.get(modname)
        if tm is None:
            return None
        return tm.functions.get(attr)

    def resolve_chain(self, mod: ModuleInfo, scope: Optional[_Scope],
                      chain: Tuple[str, ...]) -> List[ast.AST]:
        """In-project function nodes a dotted call name may refer to."""
        head = chain[0]
        if len(chain) == 1:
            cur = scope
            while cur is not None:
                if head in cur.locals:
                    return [cur.locals[head]]
                cur = cur.owner._gl if cur.owner is not None else None
            if head in mod.functions:
                return [mod.functions[head]]
            if head in mod.from_imports:
                src, attr = mod.from_imports[head]
                got = self._module_attr(src, attr)
                if got is not None:
                    return [got]
            return []
        if head == "self" and scope is not None and scope.cls:
            got = self._lookup_method(mod, scope.cls, chain[1])
            if got is not None and len(chain) == 2:
                return [got]
            return []
        if head in mod.classes and len(chain) == 2:
            got = self._lookup_method(mod, head, chain[1])
            return [got] if got is not None else []
        if head in mod.imports:
            target = ".".join([mod.imports[head]] + list(chain[1:-1]))
            got = self._module_attr(target, chain[-1])
            return [got] if got is not None else []
        if head in mod.from_imports:
            src, attr = mod.from_imports[head]
            base = src + "." + attr if src else attr
            # `from . import sibling` -> sibling.fn(...)
            target = ".".join([base] + list(chain[1:-1]))
            got = self._module_attr(target, chain[-1])
            if got is not None:
                return [got]
            # `from .mod import Cls` -> Cls.static(...)
            smod = self.modules.get(src)
            if smod is not None and attr in smod.classes and len(chain) == 2:
                got = self._lookup_method(smod, attr, chain[1])
                if got is not None:
                    return [got]
        return []

    def const_str(self, mod: ModuleInfo, scope: Optional[_Scope],
                  node) -> Optional[str]:
        """String value of a Constant or a Name bound to a module/class
        level string constant."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if scope is not None and scope.cls is not None:
                got = mod.class_consts.get((scope.cls, node.id))
                if isinstance(got, str):
                    return got
            got = mod.consts.get(node.id)
            if isinstance(got, str):
                return got
        return None

    # -- per-function facts ----------------------------------------------
    def facts(self, fn: ast.AST) -> FunctionFacts:
        cached = self._facts.get(id(fn))
        if cached is not None:
            return cached
        facts = self._extract_facts(fn)
        self._facts[id(fn)] = facts
        return facts

    def _extract_facts(self, fn: ast.AST) -> FunctionFacts:
        scope: _Scope = fn._gl  # type: ignore[attr-defined]
        mod = scope.mod
        facts = FunctionFacts()

        skip_keys: Set[str] = set()
        args = getattr(fn, "args", None)
        if args is not None:
            for a in list(args.posonlyargs) + list(args.args) + \
                    list(args.kwonlyargs):
                skip_keys.add(a.arg)
            if args.vararg:
                skip_keys.add(args.vararg.arg)
            if args.kwarg:
                skip_keys.add(args.kwarg.arg)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                    ast.GeneratorExp)):
                    for gen in sub.generators:
                        for t in ast.walk(gen.target):
                            if isinstance(t, ast.Name):
                                skip_keys.add(t.id)
                elif isinstance(sub, ast.For):
                    for t in ast.walk(sub.target):
                        if isinstance(t, ast.Name):
                            skip_keys.add(t.id)

        def env_key(call, kind):
            # kind: "get" (key is args[0]) / "getenv" / "get_env"
            if not call.args:
                return
            key = self.const_str(mod, scope, call.args[0])
            if key is not None:
                facts.env_reads.append(EnvRead(key, call.lineno))
                return
            node = call.args[0]
            if isinstance(node, ast.Name) and node.id in skip_keys:
                return  # keyed accessor pattern (get_env/_step_env style)
            facts.env_reads.append(EnvRead(None, call.lineno))

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # nested defs are separate analysis units
            if isinstance(node, ast.Call):
                chain = _dotted(node.func)
                canon = self.canonical(mod, chain) if chain else None
                targets = self.resolve_chain(mod, scope, chain) \
                    if chain else []
                facts.calls.append(CallSite(
                    node, node.lineno, chain, canon, targets))
                # env reads
                if canon in ("os.environ.get", "os.getenv"):
                    env_key(node, "get")
                elif chain and chain[-1] == "get_env" and \
                        fn_name(fn) != "get_env":
                    env_key(node, "get_env")
                elif chain and len(chain) >= 2 and \
                        chain[-2:] == ("environ", "get") and \
                        (chain[0] == "os" or canon is None and
                         chain[0] == "environ"):
                    env_key(node, "get")
                # telemetry bump: G.inc() / G.labels(...).inc()
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _BUMP_ATTRS:
                    base = node.func.value
                    name = None
                    if isinstance(base, ast.Call):
                        inner = _dotted(base.func)
                        if inner and inner[-1] == "labels" and \
                                len(inner) == 2:
                            name = inner[0]
                    elif isinstance(base, ast.Name):
                        name = base.id
                    if name is not None and name in mod.instruments:
                        kind, metric, _ = mod.instruments[name]
                        facts.bumps.append(Bump(name, metric, node.lineno))
                # function-valued arguments become edges (traced
                # callbacks) — except through jax host-callback APIs,
                # whose targets run on the host per call
                if not (chain and chain[-1] in _HOST_CALLBACKS):
                    for arg in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Name):
                            refs = self.resolve_chain(mod, scope, (arg.id,))
                            if refs:
                                facts.calls.append(CallSite(
                                    arg, arg.lineno, (arg.id,), None,
                                    refs, is_ref=True))
            elif isinstance(node, ast.Subscript):
                chain = _dotted(node.value)
                canon = self.canonical(mod, chain) if chain else None
                if canon == "os.environ" or \
                        (chain and chain[-2:] == ("os", "environ")):
                    key = self.const_str(mod, scope, node.slice)
                    if key is not None:
                        facts.env_reads.append(EnvRead(key, node.lineno))
                    elif not (isinstance(node.slice, ast.Name) and
                              node.slice.id in skip_keys):
                        facts.env_reads.append(EnvRead(None, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in body:
            visit(stmt)
        return facts

    # -- reachability ------------------------------------------------------
    def reachable(self, roots: Iterable[ast.AST],
                  max_nodes: int = 5000) -> List[ast.AST]:
        """Functions reachable from ``roots`` through resolvable calls
        (lambdas are transparent: their bodies belong to the enclosing
        function's facts)."""
        seen_ids: Set[int] = set()
        out: List[ast.AST] = []
        stack = list(roots)
        while stack and len(out) < max_nodes:
            fn = stack.pop()
            if id(fn) in seen_ids:
                continue
            seen_ids.add(id(fn))
            out.append(fn)
            for site in self.facts(fn).calls:
                for tgt in site.targets:
                    if id(tgt) not in seen_ids:
                        stack.append(tgt)
        return out

    # -- traced-root discovery (shared by GL001/GL002/GL004) --------------
    def jit_roots(self) -> List[Tuple[str, ModuleInfo, ast.AST, int]]:
        """(kind, module, function-node, line) for every function that is
        handed to a tracer: jax.jit / custom_vjp(+defvjp) / pallas_call /
        shard_map / platform_dependent."""
        out = []
        seen: Set[int] = set()

        def add(kind, mod, fnode, line):
            if fnode is None or id(fnode) in seen:
                return
            seen.add(id(fnode))
            out.append((kind, mod, fnode, line))

        def callable_arg(mod, scope, node):
            if isinstance(node, ast.Lambda):
                return node
            if isinstance(node, ast.Name):
                got = self.resolve_chain(mod, scope, (node.id,))
                return got[0] if got else None
            chain = _dotted(node)
            if chain:
                got = self.resolve_chain(mod, scope, chain)
                return got[0] if got else None
            return None

        for mod in self.modules.values():
            for fn in list(mod.functions.values()):
                scope: _Scope = fn._gl  # type: ignore[attr-defined]
                # decorators
                for dec in getattr(fn, "decorator_list", ()):
                    canon = None
                    call = None
                    if isinstance(dec, ast.Call):
                        call = dec
                        canon = self.canonical(mod, _dotted(dec.func))
                        if canon and canon.endswith("functools.partial") or \
                                canon == "functools.partial" or \
                                (canon or "").endswith(".partial"):
                            if call.args:
                                inner = self.canonical(
                                    mod, _dotted(call.args[0]))
                                if inner and (
                                        inner.endswith(".jit") or
                                        inner.endswith("custom_vjp")):
                                    add("jit" if inner.endswith(".jit")
                                        else "custom_vjp",
                                        mod, fn, dec.lineno)
                            continue
                    else:
                        canon = self.canonical(mod, _dotted(dec))
                    if canon is None:
                        continue
                    if canon.endswith(".jit") and canon.startswith("jax"):
                        add("jit", mod, fn, dec.lineno)
                    elif canon.endswith("custom_vjp"):
                        add("custom_vjp", mod, fn, dec.lineno)
                # call sites inside this function
                for site in self.facts(fn).calls:
                    if site.is_ref or not site.chain:
                        continue
                    canon = site.canon or ""
                    last = site.chain[-1]
                    call = site.node
                    if (canon.startswith("jax") and canon.endswith(".jit")) \
                            or last == "jit":
                        if call.args:
                            add("jit", mod, callable_arg(
                                mod, scope, call.args[0]), call.lineno)
                    elif last == "pallas_call" or \
                            canon.endswith("pallas_call"):
                        if call.args:
                            add("pallas", mod, callable_arg(
                                mod, scope, call.args[0]), call.lineno)
                    elif last == "shard_map" or canon.endswith("shard_map"):
                        if call.args:
                            add("shard_map", mod, callable_arg(
                                mod, scope, call.args[0]), call.lineno)
                    elif last == "defvjp":
                        for arg in call.args:
                            add("custom_vjp", mod, callable_arg(
                                mod, scope, arg), call.lineno)
                    elif last == "platform_dependent" or \
                            canon.endswith("platform_dependent"):
                        for kw in call.keywords:
                            add("platform_dependent", mod, callable_arg(
                                mod, scope, kw.value), call.lineno)
        return out

    def registered_ops(self):
        """(module, op_name, env_keys, fn_node, line) for every function
        decorated with the op registry's ``@register(...)``."""
        out = []
        for mod in self.modules.values():
            for fn in mod.functions.values():
                for dec in getattr(fn, "decorator_list", ()):
                    if not isinstance(dec, ast.Call):
                        continue
                    chain = _dotted(dec.func)
                    if not chain or chain[-1] != "register":
                        continue
                    canon = self.canonical(mod, chain) or ""
                    if not (canon.endswith("registry.register") or
                            chain == ("register",)):
                        continue
                    op_name = fn_name(fn)
                    if dec.args and isinstance(dec.args[0], ast.Constant) \
                            and isinstance(dec.args[0].value, str):
                        op_name = dec.args[0].value
                    env_keys: Tuple[str, ...] = ()
                    for kw in dec.keywords:
                        if kw.arg == "env_keys":
                            env_keys = _literal_strings(kw.value) or ()
                    out.append((mod, op_name, env_keys, fn, dec.lineno))
        return out


def fn_name(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


def fn_qual(fn: ast.AST) -> str:
    scope = getattr(fn, "_gl", None)
    if scope is None:
        return fn_name(fn)
    return "%s.%s" % (scope.mod.name, scope.qual)


# -- baseline --------------------------------------------------------------

def load_baseline(path) -> List[str]:
    p = Path(path)
    if not p.exists():
        return []
    data = json.loads(p.read_text(encoding="utf-8"))
    if isinstance(data, dict):
        return list(data.get("fingerprints", []))
    return list(data)


def save_baseline(path, fingerprints: Iterable[str]) -> None:
    payload = {"version": 1, "fingerprints": sorted(set(fingerprints))}
    Path(path).write_text(
        json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def split_by_baseline(findings: Sequence[Finding], baseline: Sequence[str]):
    """-> (new, baselined, stale_fingerprints)"""
    base = set(baseline)
    new, old = [], []
    seen = set()
    for f in findings:
        fp = f.fingerprint
        seen.add(fp)
        (old if fp in base else new).append(f)
    stale = sorted(base - seen)
    return new, old, stale
