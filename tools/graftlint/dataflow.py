"""Interprocedural dataflow core shared by the graftlint checks.

Two analyses live here, both computed once per :class:`Project` and cached
on it, so every check that needs cross-function facts shares the work:

**Env-key taint** (:func:`env_taint`).  The per-function fact extractor
deliberately skips environment reads whose key is a *parameter* — the
``get_env(name)`` accessor pattern — because the read belongs to the
caller that supplied the literal.  This pass closes that gap
interprocedurally: a fixpoint marks every parameter that flows into an
env-read key (directly, or through any chain of resolvable calls), then
:func:`function_env_reads` materializes a read *at each call site* that
passes a literal key to such a parameter.  Helpers-behind-helpers —
``op() -> _flag() -> _env() -> os.environ.get(name)`` — therefore no
longer hide reads from GL001/GL002's reachability walks.

**Lock model** (:func:`lock_analysis`).  The GL003 analysis, upgraded:

* a static lock table with **constructor sites** — every
  ``threading.Lock/RLock/Condition()`` call in the tree maps to a stable
  lock id (``module.Class.attr`` / ``module.name`` for the assignment
  forms, an anonymous *family* id for dict-of-locks and other dynamic
  forms), which is what lets the runtime sanitizer
  (:mod:`mxnet_tpu.locksmith`) translate live lock objects back into the
  static graph;
* **local aliasing**: ``lk = self._lock`` followed by ``with lk:`` is
  tracked as an acquisition of ``self._lock``;
* held-set propagation through resolvable callees (bounded depth), ABBA
  edge collection, blocking-under-hot-lock findings; and
* **callback capture** for GL011: any call made while holding a lock
  whose name is callback-shaped (``on_*``, ``*_cb``, ``*callback*``,
  ``*hook*``, …) and does not resolve to a function in the tree is
  recorded with the held set.

Soundness limits (see docs/lint.md): calls that cannot be resolved
statically are skipped, never guessed; taint flows only through
positional/keyword arguments that are plain names or literals; the lock
walk models ``with`` acquisition only (the tree has no bare
``.acquire()`` discipline) and bounds callee depth at ``_MAX_DEPTH``.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .core import (EnvRead, Finding, ModuleInfo, Project, _dotted, fn_name,
                   fn_qual)

__all__ = [
    "EnvTaint", "LockAnalysis", "LockDef", "env_taint", "lock_analysis",
    "function_env_reads", "reachable_env_reads", "lock_graph",
]

# ---------------------------------------------------------------------------
# env-key taint
# ---------------------------------------------------------------------------

_ENV_GET_CANON = ("os.environ.get", "os.getenv")


def _param_info(fn) -> Tuple[List[str], Set[str]]:
    """(positional names in order, all bindable names) of a function."""
    a = getattr(fn, "args", None)
    if a is None:
        return [], set()
    pos = [p.arg for p in list(a.posonlyargs) + list(a.args)]
    allnames = set(pos) | {p.arg for p in a.kwonlyargs}
    return pos, allnames


def _own_nodes(fn):
    """All AST nodes of ``fn`` excluding nested function bodies."""
    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield child
            yield from rec(child)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        yield stmt
        yield from rec(stmt)


def _is_env_read_call(site) -> bool:
    """True when the call site is itself one of the env-read forms the
    per-function fact extractor already handles (so taint must not
    double-count it)."""
    canon = site.canon or ""
    chain = site.chain or ()
    if canon in _ENV_GET_CANON:
        return True
    if chain and chain[-1] == "get_env":
        return True
    if len(chain) >= 2 and chain[-2:] == ("environ", "get"):
        return True
    return False


class EnvTaint:
    """Fixpoint over 'this parameter is used as an env-read key'."""

    def __init__(self, project: Project):
        self.project = project
        #: id(fn) -> set of tainted parameter names
        self.key_params: Dict[int, Set[str]] = {}
        self._extra: Dict[int, List[EnvRead]] = {}
        self._all_fns: List[ast.AST] = [
            fn for mod in project.modules.values()
            for fn in mod.functions.values()]
        self._build()

    # -- construction -----------------------------------------------------
    def _direct_key_params(self, fn) -> Set[str]:
        scope = fn._gl
        mod = scope.mod
        _, params = _param_info(fn)
        if not params:
            return set()
        out: Set[str] = set()
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                chain = _dotted(node.func)
                canon = self.project.canonical(mod, chain) if chain else None
                is_env = (canon in _ENV_GET_CANON or
                          (chain and len(chain) >= 2 and
                           chain[-2:] == ("environ", "get")) or
                          (chain and chain[-1] == "get_env" and
                           fn_name(fn) != "get_env"))
                # os.environ.get(name) inside get_env itself
                if chain and chain[-1] == "get_env" and \
                        fn_name(fn) == "get_env":
                    is_env = False
                if is_env and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in params:
                    out.add(node.args[0].id)
            elif isinstance(node, ast.Subscript):
                chain = _dotted(node.value)
                canon = self.project.canonical(mod, chain) if chain else None
                if (canon == "os.environ" or
                        (chain and chain[-2:] == ("os", "environ"))) and \
                        isinstance(node.slice, ast.Name) and \
                        node.slice.id in params:
                    out.add(node.slice.id)
        return out

    def _arg_bindings(self, caller, site):
        """Yield (arg_expr, callee, callee_param_name) for a resolved call
        site (positional + keyword args mapped onto the callee
        signature)."""
        call = site.node
        if not isinstance(call, ast.Call):
            return
        for g in site.targets:
            pos, allnames = _param_info(g)
            offset = 0
            gscope = getattr(g, "_gl", None)
            if gscope is not None and gscope.cls is not None and pos and \
                    pos[0] in ("self", "cls") and site.chain and \
                    len(site.chain) > 1:
                offset = 1
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred):
                    continue
                j = i + offset
                if j < len(pos):
                    yield arg, g, pos[j]
            for kw in call.keywords:
                if kw.arg is not None and kw.arg in allnames:
                    yield kw.value, g, kw.arg

    def _build(self):
        project = self.project
        for fn in self._all_fns:
            self.key_params[id(fn)] = self._direct_key_params(fn)
        # fixpoint: caller param passed into a tainted callee param
        changed = True
        iters = 0
        while changed and iters < 20:
            changed = False
            iters += 1
            for fn in self._all_fns:
                _, params = _param_info(fn)
                if not params:
                    continue
                mine = self.key_params[id(fn)]
                for site in project.facts(fn).calls:
                    if site.is_ref or not site.targets:
                        continue
                    if _is_env_read_call(site):
                        continue
                    for arg, g, gparam in self._arg_bindings(fn, site):
                        if not (isinstance(arg, ast.Name) and
                                arg.id in params):
                            continue
                        if gparam in self.key_params.get(id(g), ()) and \
                                arg.id not in mine:
                            mine.add(arg.id)
                            changed = True

    # -- queries ----------------------------------------------------------
    def extra_reads(self, fn) -> List[EnvRead]:
        """Env reads materialized at ``fn``'s call sites: literal (or
        module-constant) keys passed to tainted parameters of callees.
        Non-literal keys that are not parameters of ``fn`` become dynamic
        reads.  Call sites the base fact extractor already records
        (``get_env`` / ``os.environ.get``) are skipped."""
        cached = self._extra.get(id(fn))
        if cached is not None:
            return cached
        scope = getattr(fn, "_gl", None)
        out: List[EnvRead] = []
        if scope is None:
            self._extra[id(fn)] = out
            return out
        mod = scope.mod
        _, params = _param_info(fn)
        for site in self.project.facts(fn).calls:
            if site.is_ref or not site.targets:
                continue
            if _is_env_read_call(site):
                continue
            for arg, g, gparam in self._arg_bindings(fn, site):
                if gparam not in self.key_params.get(id(g), ()):
                    continue
                key = self.project.const_str(mod, scope, arg)
                if key is not None:
                    out.append(EnvRead(key, site.line))
                elif isinstance(arg, ast.Name) and arg.id in params:
                    continue    # materializes in our callers instead
                else:
                    out.append(EnvRead(None, site.line))
        self._extra[id(fn)] = out
        return out


def env_taint(project: Project) -> EnvTaint:
    cached = getattr(project, "_gl_env_taint", None)
    if cached is None:
        cached = EnvTaint(project)
        project._gl_env_taint = cached  # type: ignore[attr-defined]
    return cached


def function_env_reads(project: Project, fn) -> List[EnvRead]:
    """Direct facts plus taint-materialized reads for one function."""
    return list(project.facts(fn).env_reads) + \
        env_taint(project).extra_reads(fn)


def reachable_env_reads(project: Project, root):
    """{key: (rel, line)} + [(rel, line, qual)] dynamic reads reachable
    from ``root`` through resolvable calls, env-key taint included."""
    reads: Dict[str, Tuple[str, int]] = {}
    dynamic: List[Tuple[str, int, str]] = []
    dyn_seen: Set[Tuple[str, int]] = set()
    for g in project.reachable([root]):
        scope = getattr(g, "_gl", None)
        if scope is None:
            continue
        for er in function_env_reads(project, g):
            if er.key is None:
                spot = (scope.mod.rel, er.line)
                if spot not in dyn_seen:
                    dyn_seen.add(spot)
                    dynamic.append((scope.mod.rel, er.line, fn_qual(g)))
            else:
                reads.setdefault(er.key, (scope.mod.rel, er.line))
    return reads, dynamic


# ---------------------------------------------------------------------------
# lock model
# ---------------------------------------------------------------------------

_BLOCKING_ATTRS = {
    "asnumpy": ".asnumpy() host sync",
    "block_until_ready": "block_until_ready device sync",
    "wait_to_read": "wait_to_read device sync",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "recvfrom": "socket recv",
    "recv_msg": "socket recv",
    "recv_msg_full": "socket recv",
    "accept": "socket accept",
}

# default: modules whose locks guard hot paths; overridable for fixtures
_DEFAULT_SCOPE = ("telemetry", "engine", "serving", "health")

_MAX_DEPTH = 8

# callback-shaped call names: user/registry-supplied code the module does
# not own.  Only calls that do NOT resolve to a function in the tree are
# flagged — a project-owned method named on_epoch_end is ordinary code.
_CB_CALL_RE = re.compile(
    r"(?:^|_)(?:callback|hook|listener|observer|subscriber|cb)$"
    r"|^on_[a-z0-9_]+$")
#: containers whose iteration yields callbacks: ``for cb in self._hooks:``
_CB_CONTAINER_RE = re.compile(
    r"(?:^|_)(?:callbacks?|hooks?|listeners?|observers?|subscribers?)$")


def blocking_kind(site) -> Optional[str]:
    chain, canon, call = site.chain, site.canon or "", site.node
    if not chain:
        return None
    last = chain[-1]
    if last in _BLOCKING_ATTRS:
        return _BLOCKING_ATTRS[last]
    if canon == "time.sleep":
        return "time.sleep"
    if last == "get" and len(chain) > 1 and not call.args and \
            not any(kw.arg in ("timeout", "block") for kw in call.keywords):
        return "queue.get() without timeout"
    if last == "join" and len(chain) > 1 and not call.args and \
            not call.keywords:
        return "join() without timeout"
    return None


@dataclass(frozen=True)
class LockDef:
    kind: str       # Lock / RLock / Condition
    rel: str        # repo-relative path of the constructor site
    line: int       # constructor line
    family: bool = False   # dynamically-created (dict-of-locks etc.)


class _Summary:
    __slots__ = ("acquires", "blocking")

    def __init__(self):
        self.acquires: Set[str] = set()
        # (kind, rel, line, qual) of blocking sites in fn + callees
        self.blocking: List[Tuple[str, str, int, str]] = []


class _FakeSite:
    __slots__ = ("node", "chain", "canon")

    def __init__(self, node, chain, canon):
        self.node = node
        self.chain = chain
        self.canon = canon


class LockAnalysis:
    """Whole-tree lock table + acquisition graph + blocking/callback
    findings.  Build with :func:`lock_analysis` (cached per project)."""

    def __init__(self, project: Project):
        self.project = project
        self.locks: Dict[str, LockDef] = {}       # lock id -> definition
        self.cond_alias: Dict[str, str] = {}      # condition id -> lock id
        #: (rel, ctor line) -> lock id, for EVERY ctor site in the tree
        self.sites: Dict[Tuple[str, int], str] = {}
        self.summaries: Dict[int, _Summary] = {}
        self.in_progress: Set[int] = set()
        # (a, b) -> (rel, line, qual) first site acquiring b while holding a
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.blocking_findings: List[Finding] = []
        #: (rel, line, qual, call chain string, held lock ids)
        self.callback_calls: List[
            Tuple[str, int, str, str, Tuple[str, ...]]] = []
        self.scope = tuple(project.config.get(
            "lock_scope_modules", _DEFAULT_SCOPE))
        self._summarized = False

    # -- lock definition table -------------------------------------------
    def collect_locks(self):
        pending_conds = []
        for mod in self.project.modules.values():
            # module-level globals
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    kind = self._ctor_kind(mod, node.value)
                    if not kind:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            lid = "%s.%s" % (mod.name, tgt.id)
                            self._add(lid, kind, mod, node.value,
                                      pending_conds)
                            break
            # self.X = threading.Lock() inside methods
            for fn in mod.functions.values():
                scope = fn._gl
                if scope.cls is None:
                    continue
                for node in _own_nodes(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    kind = self._ctor_kind(mod, node.value)
                    if not kind:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            lid = "%s.%s.%s" % (mod.name, scope.cls,
                                                tgt.attr)
                            self._add(lid, kind, mod, node.value,
                                      pending_conds)
                            break
        # resolve Condition(self.X) aliases now the lock table is complete
        for lid, mod, call in pending_conds:
            kind_rel_line = ("Condition", mod.rel, call.lineno)
            if call.args:
                arg = call.args[0]
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    owner = lid.rsplit(".", 1)[0]
                    target = "%s.%s" % (owner, arg.attr)
                    if target in self.locks:
                        self.cond_alias[lid] = target
                        self.sites.setdefault(
                            (mod.rel, call.lineno), target)
                        continue
            self.locks.setdefault(lid, LockDef(*kind_rel_line))
            self.sites.setdefault((mod.rel, call.lineno), lid)
        # every remaining ctor site becomes an anonymous family: a lock
        # created dynamically (dict-of-locks, per-call) still needs a
        # static identity for the runtime sanitizer's site mapping
        for mod in self.project.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._ctor_kind(mod, node)
                if not kind:
                    continue
                key = (mod.rel, node.lineno)
                if key in self.sites:
                    continue
                lid = "%s.<%s@%d>" % (mod.name, kind.lower(), node.lineno)
                self.locks.setdefault(
                    lid, LockDef(kind, mod.rel, node.lineno, family=True))
                self.sites[key] = lid

    def _add(self, lid, kind, mod, value, pending_conds):
        if kind == "Condition":
            pending_conds.append((lid, mod, value))
        else:
            self.locks[lid] = LockDef(kind, mod.rel, value.lineno)
            self.sites.setdefault((mod.rel, value.lineno), lid)

    def _ctor_kind(self, mod, value) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        chain = _dotted(value.func)
        if not chain or chain[-1] not in ("Lock", "RLock", "Condition"):
            return None
        canon = self.project.canonical(mod, chain) or ""
        if "threading" in canon or chain[0] in ("threading", "_threading") \
                or len(chain) == 1:
            return chain[-1]
        return None

    # -- acquisition resolution ------------------------------------------
    def _resolve_lock_expr(self, mod, scope, expr) -> Optional[str]:
        lid = None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and scope is not None and scope.cls is not None:
            lid = "%s.%s.%s" % (mod.name, scope.cls, expr.attr)
        elif isinstance(expr, ast.Name):
            if expr.id in mod.from_imports:
                src, attr = mod.from_imports[expr.id]
                lid = "%s.%s" % (src, attr)
            else:
                lid = "%s.%s" % (mod.name, expr.id)
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base in mod.imports:
                lid = "%s.%s" % (mod.imports[base], expr.attr)
        if lid is None:
            return None
        lid = self.cond_alias.get(lid, lid)
        return lid if lid in self.locks else None

    def acquire_id(self, mod, scope, expr,
                   aliases: Optional[Dict[str, str]] = None) -> Optional[str]:
        if aliases and isinstance(expr, ast.Name) and expr.id in aliases:
            return aliases[expr.id]
        return self._resolve_lock_expr(mod, scope, expr)

    def in_scope(self, lock_id: str) -> bool:
        modpart = lock_id.lower()
        return any(s in modpart for s in self.scope)

    # -- per-function summaries ------------------------------------------
    def summarize_all(self):
        if self._summarized:
            return
        self._summarized = True
        if not self.locks and not self.sites:
            self.collect_locks()
        for mod in self.project.modules.values():
            for fn in mod.functions.values():
                self.summarize(fn)

    def summarize(self, fn, depth=0) -> _Summary:
        cached = self.summaries.get(id(fn))
        if cached is not None:
            return cached
        s = _Summary()
        if depth > _MAX_DEPTH or id(fn) in self.in_progress:
            return s
        self.in_progress.add(id(fn))
        self._walk_fn(fn, s, depth)
        self.in_progress.discard(id(fn))
        self.summaries[id(fn)] = s
        return s

    def _walk_fn(self, fn, summary: _Summary, depth):
        scope = getattr(fn, "_gl", None)
        if scope is None:
            return
        mod = scope.mod
        qual = fn_qual(fn)
        project = self.project

        # local lock aliases (lk = self._lock) and callback loop vars
        # (for cb in self._callbacks:), collected in one prepass
        aliases: Dict[str, str] = {}
        cb_vars: Set[str] = set()
        for node in _own_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                lid = self._resolve_lock_expr(mod, scope, node.value)
                if lid is not None:
                    aliases[node.targets[0].id] = lid
            elif isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name):
                ichain = _dotted(node.iter)
                if ichain and _CB_CONTAINER_RE.search(ichain[-1]):
                    cb_vars.add(node.target.id)

        def record_blocking(kind, line, held):
            site = (kind, mod.rel, line, qual)
            if len(summary.blocking) < 50:
                summary.blocking.append(site)
            self._maybe_flag(site, held)

        def maybe_callback(node, chain, held):
            if not held or not chain:
                return
            name = chain[-1]
            shaped = bool(_CB_CALL_RE.search(name)) or \
                (len(chain) == 1 and name in cb_vars)
            if not shaped:
                return
            if project.resolve_chain(mod, scope, chain):
                return  # project-owned function, not a user callback
            self.callback_calls.append(
                (mod.rel, node.lineno, qual, ".".join(chain), tuple(held)))

        def handle_call(node, held):
            chain = _dotted(node.func)
            canon = project.canonical(mod, chain) if chain else None
            site = _FakeSite(node, chain, canon)
            kind = blocking_kind(site)
            if kind:
                record_blocking(kind, node.lineno, held)
            if not chain:
                return
            maybe_callback(node, chain, held)
            for tgt in project.resolve_chain(mod, scope, chain):
                sub = self.summarize(tgt, depth + 1)
                summary.acquires |= sub.acquires
                for h in held:
                    for a in sub.acquires:
                        if a != h:
                            self.edges.setdefault(
                                (h, a), (mod.rel, node.lineno, qual))
                for bsite in sub.blocking:
                    if len(summary.blocking) < 50:
                        summary.blocking.append(bsite)
                    self._maybe_flag(bsite, held)

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            handle_call(sub, held)
                    lid = self.acquire_id(mod, scope, item.context_expr,
                                          aliases)
                    if lid is not None:
                        for h in held:
                            if h != lid:
                                self.edges.setdefault(
                                    (h, lid),
                                    (mod.rel, node.lineno, qual))
                        acquired.append(lid)
                        summary.acquires.add(lid)
                new_held = held + tuple(a for a in acquired
                                        if a not in held)
                for b in node.body:
                    visit(b, new_held)
                return
            if isinstance(node, ast.Call):
                handle_call(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            visit(stmt, ())

    def _maybe_flag(self, bsite, held):
        if not held:
            return
        kind, rel, line, qual = bsite
        for h in held:
            if self.in_scope(h):
                self.blocking_findings.append(Finding(
                    "GL003", rel, line,
                    "%s in %s while holding %s — a hot-path lock must "
                    "never wait on the device or the network"
                    % (kind, qual, h),
                    "blocking:%s:%s:%s" % (kind.split()[0], qual, h)))
                return


def lock_analysis(project: Project) -> LockAnalysis:
    """Shared, fully-summarized LockAnalysis for a project (GL003, GL011
    and the lock-graph export all reuse one instance)."""
    cached = getattr(project, "_gl_lock_analysis", None)
    if cached is None:
        cached = LockAnalysis(project)
        cached.collect_locks()
        cached.summarize_all()
        project._gl_lock_analysis = cached  # type: ignore[attr-defined]
    return cached


def lock_graph(project: Project) -> Dict:
    """JSON-able static lock graph for the runtime sanitizer
    (``python -m tools.graftlint --dump-lock-graph``): the lock table with
    constructor sites, the site->id mapping, and the acquisition edges."""
    an = lock_analysis(project)
    return {
        "version": 1,
        "locks": {
            lid: {"kind": d.kind, "rel": d.rel, "line": d.line,
                  "family": d.family}
            for lid, d in sorted(an.locks.items())},
        "sites": {"%s:%d" % site: lid
                  for site, lid in sorted(an.sites.items())},
        "edges": sorted([list(pair) for pair in an.edges]),
    }
