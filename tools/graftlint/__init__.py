"""graftlint: whole-program static analyzer for mxnet_tpu's contracts.

Checks (see docs/lint.md):
  GL001  env reads on trace paths must join the jit cache key
  GL002  tracer purity: no host side effects in traced code
  GL003  lock discipline: consistent order, no blocking under hot locks
  GL004  donation contract: donate_argnums pairs with pool/audit
  GL005  metric registry: telemetry names match docs/observability.md
  GL006  named scopes on telemetry/profiling blocks
  GL007  env-knob registry: MXNET_* reads match docs/knobs.md
  GL008  thread discipline: every thread daemon or provably joined
  GL009  kvstore wire contract: client and server halves match
  GL010  runlog events: emitted names match the documented table
  GL011  lock-callback discipline: no callbacks invoked under a lock

GL001-GL003 and GL011 run over a shared interprocedural dataflow core
(tools/graftlint/dataflow.py): call-graph reachability with env-key
taint propagation and a held-lock-set lock model, built once per
Project and reused across checks.

Run: ``python -m tools.graftlint`` (see --help).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .core import (Finding, Project, load_baseline, save_baseline,
                   split_by_baseline)
from .checks import ALL_CHECKS

__all__ = ["Project", "Finding", "run_checks", "LintResult",
           "ALL_CHECKS", "DEFAULT_BASELINE"]

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # actionable
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    checks_run: List[str] = field(default_factory=list)

    @property
    def all_raw(self) -> List[Finding]:
        return self.findings + self.baselined


def run_checks(project: Project, checks: Optional[Sequence[str]] = None,
               baseline: Optional[Sequence[str]] = None) -> LintResult:
    """Run the selected checks and fold in suppressions + baseline."""
    selected = [c.upper() for c in (checks or sorted(ALL_CHECKS))]
    unknown = [c for c in selected if c not in ALL_CHECKS]
    if unknown:
        raise ValueError("unknown checks: %s (known: %s)"
                         % (", ".join(unknown), ", ".join(sorted(ALL_CHECKS))))
    raw: List[Finding] = list(project.parse_errors)
    for code in selected:
        raw.extend(ALL_CHECKS[code].run(project))

    result = LintResult(checks_run=selected)
    mods_by_rel: Dict[str, object] = {m.rel: m
                                      for m in project.modules.values()}
    kept: List[Finding] = []
    used_suppressions = set()
    for f in raw:
        mod = mods_by_rel.get(f.path)
        sup = mod.suppression_for(f.line, f.code) if mod else None
        if sup is not None:
            used_suppressions.add((sup.path, sup.line))
            result.suppressed.append(f)
        else:
            kept.append(f)

    # a suppression without a reason is itself a finding (GL000)
    for mod in project.modules.values():
        for line, sup in sorted(mod.suppressions().items()):
            if not sup.reason:
                kept.append(Finding(
                    "GL000", sup.path, line,
                    "graftlint suppression without a reason — write "
                    "`# graftlint: disable=%s -- <why this is safe>`"
                    % ",".join(sorted(sup.codes)),
                    "no-reason:%s" % ",".join(sorted(sup.codes))))

    new, old, stale = split_by_baseline(kept, baseline or [])
    # a baseline entry can only be judged stale by the check that owns
    # it — subset runs must not flag the other checks' entries
    stale = [fp for fp in stale if fp.split("|", 1)[0] in selected]
    result.findings = sorted(new, key=lambda f: (f.path, f.line, f.code))
    result.baselined = sorted(old, key=lambda f: (f.path, f.line, f.code))
    result.stale_baseline = stale
    return result
