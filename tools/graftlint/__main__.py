"""CLI: ``python -m tools.graftlint [options]``.

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage or internal error.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

from . import (ALL_CHECKS, DEFAULT_BASELINE, Project, run_checks)
from .checks import DESCRIPTIONS
from .core import load_baseline, save_baseline


def _find_root(start: Path) -> Path:
    cur = start.resolve()
    for cand in (cur,) + tuple(cur.parents):
        if (cand / "mxnet_tpu").is_dir():
            return cand
    return cur


def _changed_paths(root: Path, ref: str):
    """Repo-relative posix paths changed vs ``ref`` plus untracked files,
    or None when git fails (not a repo, bad ref)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref],
            cwd=str(root), capture_output=True, text=True, timeout=30)
        status = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=str(root), capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    paths = {p.strip() for p in diff.stdout.splitlines() if p.strip()}
    for line in status.stdout.splitlines():
        if len(line) > 3:
            paths.add(line[3:].split(" -> ")[-1].strip().strip('"'))
    return paths


def _sarif(result, root: Path) -> dict:
    """SARIF 2.1.0 log: one run, one rule per check, findings (non-
    baselined, non-suppressed) as results with the line-free fingerprint
    so SARIF-aware CI dedups across line churn like the baseline does."""
    rules = [{"id": code,
              "shortDescription": {"text": DESCRIPTIONS[code]},
              "helpUri": "docs/lint.md"}
             for code in sorted(ALL_CHECKS)]
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {"primary": f.fingerprint},
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "graftlint",
                                "informationUri": "docs/lint.md",
                                "rules": rules}},
            "originalUriBaseIds": {"SRCROOT": {"uri": root.as_uri() + "/"}},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="whole-program static analyzer for mxnet_tpu's "
                    "jit-cache, tracer-purity, lock, donation, metric, "
                    "env-knob, thread, wire and runlog contracts "
                    "(docs/lint.md)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detect)")
    ap.add_argument("--checks", default=None,
                    help="comma-separated subset, e.g. GL001,GL003 "
                         "(default: all)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/graftlint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as live findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--changed-only", metavar="GIT_REF", default=None,
                    help="report only findings in files changed vs the "
                         "given git ref (plus untracked files); the "
                         "analysis itself stays whole-program, and stale-"
                         "baseline enforcement is skipped")
    ap.add_argument("--write-knobs", action="store_true",
                    help="regenerate the table in docs/knobs.md from the "
                         "tree's MXNET_* reads (preserves the description "
                         "column) and exit")
    ap.add_argument("--dump-lock-graph", action="store_true",
                    help="print the static lock-acquisition graph as JSON "
                         "(consumed by the MXNET_LOCKCHECK runtime "
                         "sanitizer) and exit")
    ap.add_argument("--smoke", action="store_true",
                    help="one-line summary only (for the verify recipe)")
    ap.add_argument("--list-checks", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checks:
        for code in sorted(ALL_CHECKS):
            print("%s  %s" % (code, DESCRIPTIONS[code]))
        return 0

    t0 = time.time()
    root = Path(args.root) if args.root else _find_root(Path.cwd())
    if not (root / "mxnet_tpu").is_dir():
        print("graftlint: no mxnet_tpu package under %s" % root,
              file=sys.stderr)
        return 2

    checks = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]

    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
    baseline = [] if args.no_baseline else load_baseline(baseline_path)

    try:
        project = Project(root)
    except ValueError as exc:
        print("graftlint: %s" % exc, file=sys.stderr)
        return 2

    if args.dump_lock_graph:
        from .dataflow import lock_graph
        print(json.dumps(lock_graph(project), indent=2, sort_keys=True))
        return 0

    if args.write_knobs:
        from .checks.gl007_env_knobs import render_knobs_md
        knobs_path = root / "docs" / "knobs.md"
        existing = knobs_path.read_text(encoding="utf-8") \
            if knobs_path.exists() else None
        knobs_path.parent.mkdir(parents=True, exist_ok=True)
        knobs_path.write_text(render_knobs_md(project, existing),
                              encoding="utf-8")
        from .checks.gl007_env_knobs import collect_env_knobs
        print("graftlint: wrote %d knobs to %s"
              % (len(collect_env_knobs(project)), knobs_path))
        return 0

    try:
        result = run_checks(project, checks=checks, baseline=baseline)
    except ValueError as exc:
        print("graftlint: %s" % exc, file=sys.stderr)
        return 2

    if args.write_baseline:
        save_baseline(baseline_path,
                      [f.fingerprint for f in result.all_raw])
        print("graftlint: wrote %d fingerprints to %s"
              % (len(result.all_raw), baseline_path))
        return 0

    if args.changed_only is not None:
        changed = _changed_paths(root, args.changed_only)
        if changed is None:
            print("graftlint: cannot resolve changed files vs %r "
                  "(not a git checkout, or bad ref)" % args.changed_only,
                  file=sys.stderr)
            return 2
        result.findings = [f for f in result.findings if f.path in changed]
        result.stale_baseline = []

    elapsed = time.time() - t0
    summary = ("graftlint: %d finding(s), %d baselined, %d suppressed, "
               "%d stale baseline entr%s — %d modules in %.2fs"
               % (len(result.findings), len(result.baselined),
                  len(result.suppressed), len(result.stale_baseline),
                  "y" if len(result.stale_baseline) == 1 else "ies",
                  len(project.modules), elapsed))

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "root": str(root),
            "checks": result.checks_run,
            "findings": [f.as_dict() for f in result.findings],
            "baselined": [f.as_dict() for f in result.baselined],
            "suppressed": [f.as_dict() for f in result.suppressed],
            "stale_baseline": result.stale_baseline,
            "summary": {
                "findings": len(result.findings),
                "baselined": len(result.baselined),
                "suppressed": len(result.suppressed),
                "stale_baseline": len(result.stale_baseline),
                "modules": len(project.modules),
                "seconds": round(elapsed, 3),
            },
        }, indent=2))
    elif args.format == "sarif":
        print(json.dumps(_sarif(result, root), indent=2))
    elif args.smoke:
        print(summary)
    else:
        for f in result.findings:
            print("%s:%d: %s %s" % (f.path, f.line, f.code, f.message))
        if result.stale_baseline:
            print("stale baseline entries (fix landed — remove them):")
            for fp in result.stale_baseline:
                print("  %s" % fp)
        print(summary)

    return 1 if (result.findings or result.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
