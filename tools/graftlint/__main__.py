"""CLI: ``python -m tools.graftlint [options]``.

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage or internal error.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from . import (ALL_CHECKS, DEFAULT_BASELINE, Project, run_checks)
from .checks import DESCRIPTIONS
from .core import load_baseline, save_baseline


def _find_root(start: Path) -> Path:
    cur = start.resolve()
    for cand in (cur,) + tuple(cur.parents):
        if (cand / "mxnet_tpu").is_dir():
            return cand
    return cur


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="whole-program static analyzer for mxnet_tpu's "
                    "jit-cache, tracer-purity, lock, donation and metric "
                    "contracts (docs/lint.md)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detect)")
    ap.add_argument("--checks", default=None,
                    help="comma-separated subset, e.g. GL001,GL003 "
                         "(default: all)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/graftlint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as live findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--smoke", action="store_true",
                    help="one-line summary only (for the verify recipe)")
    ap.add_argument("--list-checks", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checks:
        for code in sorted(ALL_CHECKS):
            print("%s  %s" % (code, DESCRIPTIONS[code]))
        return 0

    t0 = time.time()
    root = Path(args.root) if args.root else _find_root(Path.cwd())
    if not (root / "mxnet_tpu").is_dir():
        print("graftlint: no mxnet_tpu package under %s" % root,
              file=sys.stderr)
        return 2

    checks = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]

    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
    baseline = [] if args.no_baseline else load_baseline(baseline_path)

    try:
        project = Project(root)
        result = run_checks(project, checks=checks, baseline=baseline)
    except ValueError as exc:
        print("graftlint: %s" % exc, file=sys.stderr)
        return 2

    if args.write_baseline:
        save_baseline(baseline_path,
                      [f.fingerprint for f in result.all_raw])
        print("graftlint: wrote %d fingerprints to %s"
              % (len(result.all_raw), baseline_path))
        return 0

    elapsed = time.time() - t0
    summary = ("graftlint: %d finding(s), %d baselined, %d suppressed, "
               "%d stale baseline entr%s — %d modules in %.2fs"
               % (len(result.findings), len(result.baselined),
                  len(result.suppressed), len(result.stale_baseline),
                  "y" if len(result.stale_baseline) == 1 else "ies",
                  len(project.modules), elapsed))

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "root": str(root),
            "checks": result.checks_run,
            "findings": [f.as_dict() for f in result.findings],
            "baselined": [f.as_dict() for f in result.baselined],
            "suppressed": [f.as_dict() for f in result.suppressed],
            "stale_baseline": result.stale_baseline,
            "summary": {
                "findings": len(result.findings),
                "baselined": len(result.baselined),
                "suppressed": len(result.suppressed),
                "stale_baseline": len(result.stale_baseline),
                "modules": len(project.modules),
                "seconds": round(elapsed, 3),
            },
        }, indent=2))
    elif args.smoke:
        print(summary)
    else:
        for f in result.findings:
            print("%s:%d: %s %s" % (f.path, f.line, f.code, f.message))
        if result.stale_baseline:
            print("stale baseline entries (fix landed — remove them):")
            for fp in result.stale_baseline:
                print("  %s" % fp)
        print(summary)

    return 1 if (result.findings or result.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
