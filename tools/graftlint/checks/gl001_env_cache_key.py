"""GL001: environment reads on a trace path must join the jit cache key.

An ``os.environ``/``os.getenv``/``get_env`` read reachable from a traced
program builder is baked into the XLA program at trace time.  If the key is
not part of the program's cache key (``env_keys`` on a registered op,
``STEP_ENV_KEYS`` on the executor step programs), toggling the flag later
silently serves a stale program.  Both directions are checked: undeclared
reachable reads, and declared keys with no reachable read (a stale
declaration widens every cache key for nothing).
"""
from __future__ import annotations

from ..core import Finding, Project, fn_qual
from ..dataflow import function_env_reads, reachable_env_reads

CODE = "GL001"
TITLE = "env-cache-key: traced env reads must be declared in the cache key"

# interprocedural reachable-reads collection (env-key taint included:
# a literal key passed through any chain of keyed accessors counts as a
# read at the outermost call site) lives in ..dataflow
_collect_reads = reachable_env_reads


def run(project: Project):
    findings = []

    # -- A) registered ops: env_keys vs reachable reads -------------------
    for mod, op_name, env_keys, fn, line in project.registered_ops():
        reads, dynamic = _collect_reads(project, fn)
        declared = set(env_keys)
        for key in sorted(set(reads) - declared):
            rel, rline = reads[key]
            findings.append(Finding(
                CODE, rel, rline,
                "env var %r is read on the trace path of op %r but is not "
                "in its env_keys — the op's jit cache will serve a stale "
                "program after the flag changes" % (key, op_name),
                "undeclared:%s:op:%s" % (key, op_name)))
        for key in sorted(declared - set(reads)):
            findings.append(Finding(
                CODE, mod.rel, line,
                "op %r declares env_keys entry %r but no read of it is "
                "reachable from the op function — stale declaration"
                % (op_name, key),
                "stale:%s:op:%s" % (key, op_name)))
        for rel, rline, qual in dynamic:
            findings.append(Finding(
                CODE, rel, rline,
                "dynamic (non-literal) environment read in %s is on the "
                "trace path of op %r and cannot join the jit cache key"
                % (qual, op_name),
                "dynamic:%s:op:%s" % (qual, op_name)))

    # -- B) step programs: STEP_ENV_KEYS ----------------------------------
    step_keys = {}
    for mod in project.modules.values():
        for (cls, name), val in mod.class_consts.items():
            if name == "STEP_ENV_KEYS" and isinstance(val, tuple):
                for k in val:
                    step_keys.setdefault(k, (mod, cls))
        val = mod.consts.get("STEP_ENV_KEYS")
        if isinstance(val, tuple):
            for k in val:
                step_keys.setdefault(k, (mod, None))

    if step_keys:
        # every declared step key must be read (as a literal, possibly via
        # a module constant) somewhere in the tree
        read_anywhere = set()
        for mod in project.modules.values():
            for fn in mod.functions.values():
                for er in function_env_reads(project, fn):
                    if er.key is not None:
                        read_anywhere.add(er.key)
        for key in sorted(step_keys):
            if key not in read_anywhere:
                mod, cls = step_keys[key]
                findings.append(Finding(
                    CODE, mod.rel, 1,
                    "STEP_ENV_KEYS entry %r is never read anywhere in the "
                    "tree — stale declaration widens the step program "
                    "cache key for nothing" % key,
                    "stale-step:%s" % key))

        # jit roots in modules that participate in the step-key contract:
        # reachable MXNET_* env reads must be covered by STEP_ENV_KEYS
        step_mods = {mod.name for mod in project.modules.values()
                     if any("STEP_ENV_KEYS" in ln for ln in mod.lines)}
        for kind, mod, fnode, line in project.jit_roots():
            if mod.name not in step_mods or kind != "jit":
                continue
            reads, _ = _collect_reads(project, fnode)
            for key in sorted(reads):
                if not key.startswith("MXNET_"):
                    continue
                if key in step_keys:
                    continue
                rel, rline = reads[key]
                findings.append(Finding(
                    CODE, rel, rline,
                    "env var %r is read inside a step-program trace (%s) "
                    "but is not in STEP_ENV_KEYS — the cached step program "
                    "goes stale when it changes" % (key, fn_qual(fnode)),
                    "undeclared-step:%s:%s" % (key, fn_qual(fnode))))
    return findings
