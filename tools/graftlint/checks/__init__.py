"""Check registry: maps GLnnn codes to check modules."""
from __future__ import annotations

from . import (gl001_env_cache_key, gl002_tracer_purity,
               gl003_lock_discipline, gl004_donation, gl005_metric_registry,
               gl006_named_scope, gl007_env_knobs, gl008_thread_discipline,
               gl009_wire_contract, gl010_runlog_events, gl011_lock_callbacks)

ALL_CHECKS = {
    mod.CODE: mod
    for mod in (gl001_env_cache_key, gl002_tracer_purity,
                gl003_lock_discipline, gl004_donation,
                gl005_metric_registry, gl006_named_scope,
                gl007_env_knobs, gl008_thread_discipline,
                gl009_wire_contract, gl010_runlog_events,
                gl011_lock_callbacks)
}

DESCRIPTIONS = {mod.CODE: mod.TITLE for mod in ALL_CHECKS.values()}
