"""GL008: every thread is daemon or provably joined.

A non-daemon ``threading.Thread`` that nobody joins keeps the process
alive after main exits — the classic "probe hangs at shutdown" bug the
fault-tolerance and serving PRs each dodged by hand.  Two findings:

- **unjoined**: a ``threading.Thread`` construction (including
  instantiations of project classes that subclass ``Thread``) that is
  neither daemonized (``daemon=True`` in the constructor, a
  ``super().__init__(daemon=True)`` in the subclass, or a later
  ``x.daemon = True`` assignment) nor joined: for a thread bound to
  ``self.X`` or a local name the check requires a ``X.join(...)`` call
  somewhere in the same module; for unbound forms (list comprehensions,
  fire-and-forget chains) any ``.join(`` call in the module counts.
- **hang**: a non-daemon thread whose target (or subclass ``run``)
  can reach a timeout-less ``queue.get()`` / ``.join()`` — the shutdown
  path then has no bounded way to stop it.

Daemon threads are exempt from both (the interpreter kills them), which
matches the tree's convention: background samplers/exporters are daemon
+ Event-signalled, worker pools are daemon + sentinel-drained.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Project, _dotted, fn_qual
from ..dataflow import blocking_kind

CODE = "GL008"
TITLE = "thread discipline: every thread daemon or provably joined"


def _thread_subclasses(project: Project) -> Dict[str, Set[str]]:
    """{module_name: {class names subclassing threading.Thread}}"""
    out: Dict[str, Set[str]] = {}
    for mod in project.modules.values():
        for cls, bases in mod.class_bases.items():
            for b in bases:
                if b == "Thread" or b.endswith(".Thread"):
                    out.setdefault(mod.name, set()).add(cls)
    return out


def _class_daemonized(project: Project, mod, cls: str) -> bool:
    """True when the Thread subclass daemonizes itself: daemon=True in a
    super().__init__ call or a self.daemon = True assignment."""
    for qual, fn in mod.functions.items():
        if not qual.startswith(cls + "."):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                # covers plain chains and super().__init__(...) whose
                # receiver is itself a call
                if not (isinstance(node.func, ast.Attribute) and
                        node.func.attr == "__init__"):
                    continue
                for kw in node.keywords:
                    if kw.arg == "daemon" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value is True:
                        return True
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "daemon" and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value is True:
                        return True
    return False


def _resolve_target_fn(project: Project, mod, scope, call: ast.Call):
    """The function node a Thread's target= (or args[0] for bare
    Thread(target)) refers to, if resolvable in-project."""
    expr = None
    for kw in call.keywords:
        if kw.arg == "target":
            expr = kw.value
    if expr is None:
        return None
    chain = _dotted(expr)
    if not chain:
        return None
    got = project.resolve_chain(mod, scope, chain)
    return got[0] if got else None


def _join_targets(mod) -> Tuple[Set[str], bool]:
    """(names X with a X.join(...) call in the module, any-join-at-all)"""
    names: Set[str] = set()
    any_join = False
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            base = node.func.value
            if isinstance(base, ast.Constant):
                continue    # ", ".join(...) string joins
            chain = _dotted(base)
            if chain and (chain[0] in ("os", "posixpath", "ntpath") or
                          chain[-1] in ("path", "sep")):
                continue    # os.path.join and friends
            if chain:
                names.add(chain[-1])    # t.join() -> "t", self._t -> "_t"
            any_join = True             # threads[i].join() etc.
    return names, any_join


def _daemonized_later(mod, bound: Optional[str]) -> bool:
    """X.daemon = True somewhere in the module for the bound name."""
    if bound is None:
        return False
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                node.value.value is True:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "daemon":
                    chain = _dotted(tgt.value)
                    if chain and chain[-1] == bound:
                        return True
    return False


def _hang_sites(project: Project, root) -> List[Tuple[str, int, str]]:
    out = []
    for g in project.reachable([root]):
        scope = getattr(g, "_gl", None)
        if scope is None:
            continue
        for site in project.facts(g).calls:
            if site.is_ref:
                continue
            kind = blocking_kind(site)
            if kind in ("queue.get() without timeout",
                        "join() without timeout"):
                out.append((scope.mod.rel, site.line, kind))
    return out


def run(project: Project):
    findings = []
    subclasses = _thread_subclasses(project)
    daemon_classes: Set[Tuple[str, str]] = set()
    for mname, classes in subclasses.items():
        mod = project.modules[mname]
        for cls in classes:
            if _class_daemonized(project, mod, cls):
                daemon_classes.add((mname, cls))

    for mod in project.modules.values():
        join_names, any_join = _join_targets(mod)
        # map ctor call -> the name it is bound to (t = Thread(...) /
        # self._t = Thread(...)); unbound ctors keep None
        bound: Dict[int, Optional[str]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for tgt in node.targets:
                    name = None
                    if isinstance(tgt, ast.Name):
                        name = tgt.id
                    elif isinstance(tgt, ast.Attribute):
                        name = tgt.attr
                    if name:
                        bound[id(node.value)] = name
                        break

        for fn in mod.functions.values():
            scope = fn._gl
            for site in project.facts(fn).calls:
                call = site.node
                if site.is_ref or not site.chain or \
                        not isinstance(call, ast.Call):
                    continue
                last = site.chain[-1]
                sub_cls = None
                run_fn = None
                if last == "Thread":
                    canon = site.canon or ""
                    if not ("threading" in canon or
                            site.chain[0] in ("threading", "_threading")):
                        continue
                elif (mod.name, last) in daemon_classes:
                    continue    # self-daemonizing subclass: always fine
                elif last in subclasses.get(mod.name, ()):
                    sub_cls = last
                    run_fn = mod.functions.get(last + ".run")
                else:
                    # imported project Thread subclass
                    src = mod.from_imports.get(last)
                    if src and src[0] in subclasses and \
                            src[1] in subclasses[src[0]]:
                        if (src[0], src[1]) in daemon_classes:
                            continue
                        sub_cls = src[1]
                        smod = project.modules[src[0]]
                        run_fn = smod.functions.get(src[1] + ".run")
                    else:
                        continue

                daemon = None
                for kw in call.keywords:
                    if kw.arg == "daemon" and \
                            isinstance(kw.value, ast.Constant):
                        daemon = bool(kw.value.value)
                name = bound.get(id(call))
                if daemon is None and _daemonized_later(mod, name):
                    daemon = True
                qual = fn_qual(fn)
                what = sub_cls or "threading.Thread"
                if not daemon:
                    joined = (name in join_names) if name else any_join
                    if not joined:
                        findings.append(Finding(
                            CODE, mod.rel, call.lineno,
                            "%s constructed in %s is neither daemon=True "
                            "nor joined anywhere in this module — it will "
                            "outlive shutdown" % (what, qual),
                            "unjoined:%s:%s" % (qual, what)))
                    root = run_fn or _resolve_target_fn(
                        project, mod, scope, call)
                    if root is not None:
                        for rel, line, kind in _hang_sites(project, root):
                            findings.append(Finding(
                                CODE, rel, line,
                                "non-daemon thread (%s, started in %s) can "
                                "block forever on %s — shutdown has no "
                                "bounded way to stop it"
                                % (what, qual, kind),
                                "hang:%s:%s" % (qual, kind.split()[0])))
    # dedup (same ctor reached from several facts paths)
    uniq = {}
    for f in findings:
        uniq.setdefault(f.fingerprint, f)
    return list(uniq.values())
