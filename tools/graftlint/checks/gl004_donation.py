"""GL004: every donated jit program must pair with a donation audit.

``donate_argnums`` hands input buffers to XLA; if the caller keeps using
the old arrays the program silently aliases freed memory (or, on CPU
backends that ignore donation, leaks a full copy of the model per step).
The tree's contract (PR 5): every donate site either routes buffers
through a ``DonationPool`` take/give ledger or hands the old inputs to
``health.audit_donation`` after the first execution so the leak shows up
in ``program_donation_leaks_total``.

A donate site is paired when ``audit_donation`` or ``DonationPool``
appears in the enclosing top-level function, anywhere in the enclosing
class, or in a transitive caller (by name, up to 3 hops).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import Finding, Project, _dotted, fn_qual

CODE = "GL004"
TITLE = "donation contract: donate_argnums pairs with pool/audit handback"

_MARKERS = {"audit_donation", "DonationPool"}


def _identifiers(fn) -> Set[str]:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _outermost(fn):
    scope = fn._gl
    while scope.owner is not None:
        fn = scope.owner
        scope = fn._gl
    return fn


def _donate_sites(project: Project):
    """Yield (module, program_fn_or_enclosing_fn, line)."""
    for mod in project.modules.values():
        for fn in mod.functions.values():
            # decorators: @partial(jax.jit, donate_argnums=...) or
            # @jax.jit(..., donate_argnums=...)
            for dec in getattr(fn, "decorator_list", ()):
                if not isinstance(dec, ast.Call):
                    continue
                canon = project.canonical(mod, _dotted(dec.func)) or ""
                kws = {kw.arg for kw in dec.keywords}
                if canon.endswith(".partial") and dec.args:
                    inner = project.canonical(
                        mod, _dotted(dec.args[0])) or ""
                    if inner.endswith(".jit") and "donate_argnums" in kws:
                        yield mod, fn, dec.lineno
                elif canon.endswith(".jit") and "donate_argnums" in kws:
                    yield mod, fn, dec.lineno
            # call sites: jax.jit(fn, donate_argnums=...)
            for site in project.facts(fn).calls:
                if site.is_ref or not site.chain:
                    continue
                canon = site.canon or ""
                if not (canon.endswith(".jit") and canon.startswith("jax")
                        or site.chain[-1] == "jit"):
                    continue
                call = site.node
                if any(kw.arg == "donate_argnums" for kw in call.keywords):
                    yield mod, fn, call.lineno


def run(project: Project):
    # reverse call index: callee last-name -> calling functions
    callers: Dict[str, List] = {}
    for mod in project.modules.values():
        for fn in mod.functions.values():
            for site in project.facts(fn).calls:
                if site.chain:
                    callers.setdefault(site.chain[-1], []).append(fn)

    ident_cache: Dict[int, Set[str]] = {}

    def idents(fn) -> Set[str]:
        got = ident_cache.get(id(fn))
        if got is None:
            got = _identifiers(fn)
            ident_cache[id(fn)] = got
        return got

    findings = []
    seen = set()
    for mod, fn, line in _donate_sites(project):
        outer = _outermost(fn)
        scope = outer._gl
        detail = "donate:%s" % fn_qual(outer)
        if detail in seen:
            continue
        seen.add(detail)

        candidates = [outer]
        if scope.cls is not None:
            prefix = scope.cls + "."
            candidates.extend(
                f for q, f in mod.functions.items()
                if q.startswith(prefix) and f is not outer)
        # transitive callers by name, up to 3 hops
        frontier = [outer]
        visited = {id(outer)}
        for _ in range(3):
            names = set()
            for f in frontier:
                names.add(getattr(f, "name", ""))
                fsc = f._gl
                if fsc.cls is not None:
                    names.add(getattr(f, "name", ""))
            nxt = []
            for name in names:
                for caller in callers.get(name, ()):
                    if id(caller) not in visited:
                        visited.add(id(caller))
                        nxt.append(caller)
                        candidates.append(caller)
                        csc = caller._gl
                        if csc.cls is not None:
                            cmod = csc.mod
                            prefix = csc.cls + "."
                            for q, f2 in cmod.functions.items():
                                if q.startswith(prefix) and \
                                        id(f2) not in visited:
                                    visited.add(id(f2))
                                    candidates.append(f2)
            frontier = nxt
            if not frontier:
                break

        paired = any(idents(c) & _MARKERS for c in candidates)
        if not paired:
            findings.append(Finding(
                CODE, mod.rel, line,
                "donated program built in %s has no DonationPool take/give "
                "or health.audit_donation handback on any caller path — "
                "donation leaks will go unnoticed" % fn_qual(outer),
                detail))
    return findings
