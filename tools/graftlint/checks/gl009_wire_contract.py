"""GL009: the KVStore wire contract matches end to end.

The kvstore client and server share a frame format but not a schema
file: the client sends ``self._rpc("<cmd>", ...)`` literals, the server
dispatches on ``cmd == "<cmd>"`` literals; the client builds context
dicts (``{"r": ..., "st": ...}``), the server validates them against
``frozenset`` key tables; both sides hold a copy of the replay-guarded
op set (``_SEQ_OPS`` / ``_MUTATING``).  Each pair is a drift hazard: a
renamed cmd becomes an "unknown command" reject at runtime, a context
field added on one side becomes a loud frame error on every RPC.  This
check statically extracts both halves and diffs them:

- **cmd-unhandled** / **cmd-dead**: client cmd with no server
  comparison, server comparison no client ever sends;
- **ctx-drift**: context dict keys built by the client (incl. the
  tracing module's ``flow_out`` payload) vs the server's ``*_KEYS``
  validation table for the same wrapper key;
- **pack-parse-drift**: wrapper keys written by ``_pack_payload`` vs
  the allowed-key set in ``_parse_payload``;
- **incomplete-validation**: a ``_check_*`` context validator that
  rejects unknown keys but never checks ``set(ctx) != *_KEYS`` — it
  silently accepts frames with *missing* fields;
- **seq-ops-drift**: client ``_SEQ_OPS`` vs server ``_MUTATING``.

Extraction is purely literal — dynamically computed cmds or key sets are
invisible here, which is fine: the wire code is deliberately literal so
the contract stays greppable.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..core import Finding, Project, _dotted

CODE = "GL009"
TITLE = "kvstore wire contract: client and server halves match"


def _find_module(project: Project, suffix: str):
    for mod in project.modules.values():
        if mod.name == suffix or mod.name.endswith("." + suffix):
            return mod
    return None


def _literal_strs(node) -> Optional[List[str]]:
    """The string elements of frozenset((...)) / set / tuple / list
    literals, or None when any element is non-literal."""
    if isinstance(node, ast.Call):
        chain = _dotted(node.func)
        if chain and chain[-1] in ("frozenset", "set", "tuple") \
                and len(node.args) == 1:
            return _literal_strs(node.args[0])
        return None
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


def _named_set(mod, name: str) -> Optional[Tuple[FrozenSet[str], int]]:
    """Module- or class-level ``NAME = frozenset((...))`` assignment."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            tname = tgt.id if isinstance(tgt, ast.Name) else None
            if tname != name:
                continue
            vals = _literal_strs(node.value)
            if vals is not None:
                return frozenset(vals), node.lineno
    return None


def _client_cmds(mod) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if chain and chain[-1] == "_rpc" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                out.setdefault(node.args[0].value, node.lineno)
    return out


def _server_cmds(mod) -> Dict[str, int]:
    """Literal comparisons against a name ``cmd``: both ``cmd == "x"``
    and ``cmd in ("x", "y")`` forms."""
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not (isinstance(node.left, ast.Name) and
                node.left.id == "cmd"):
            continue
        comp = node.comparators[0]
        if isinstance(node.ops[0], ast.Eq) and \
                isinstance(comp, ast.Constant) and \
                isinstance(comp.value, str):
            out.setdefault(comp.value, node.lineno)
        elif isinstance(node.ops[0], ast.In):
            for v in _literal_strs(comp) or ():
                out.setdefault(v, node.lineno)
    return out


def _pack_mapping(server) -> Tuple[Dict[str, str], Set[str], int]:
    """From ``_pack_payload``: ({wrapper key: param name}, all wrapper
    keys written incl. the message key, def line)."""
    mapping: Dict[str, str] = {}
    keys: Set[str] = set()
    line = 1
    fn = server.functions.get("_pack_payload")
    if fn is None:
        return mapping, keys, line
    line = fn.lineno
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.slice, ast.Constant) and \
                        isinstance(tgt.slice.value, str):
                    key = tgt.slice.value
                    keys.add(key)
                    src = node.value
                    if isinstance(src, ast.Call) and src.args:
                        src = src.args[0]
                    chain = _dotted(src)
                    if chain:
                        mapping[key] = chain[-1]
    return mapping, keys, line


def _parse_allowed(server) -> Optional[Tuple[FrozenSet[str], int]]:
    """The allowed-wrapper-keys literal inside ``_parse_payload``
    (``set(hdr) - {"m", "tc", ...}``)."""
    fn = server.functions.get("_parse_payload")
    if fn is None:
        return None
    for node in ast.walk(fn):
        if isinstance(node, ast.Set):
            vals = _literal_strs(node)
            if vals and "m" in vals:
                return frozenset(vals), node.lineno
    return None


def _validators(server) -> Dict[str, Tuple[str, ast.AST]]:
    """{wrapper key: (validator fn name, fn node)} from the
    ``x = _check_y(hdr["k"])`` dispatch in ``_parse_payload``."""
    out: Dict[str, Tuple[str, ast.AST]] = {}
    fn = server.functions.get("_parse_payload")
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if not chain or not chain[-1].startswith("_check_"):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Subscript) and \
                        isinstance(arg.slice, ast.Constant) and \
                        isinstance(arg.slice.value, str):
                    vfn = server.functions.get(chain[-1])
                    if vfn is not None:
                        out[arg.slice.value] = (chain[-1], vfn)
    return out


def _validator_keys(server, vfn) -> Optional[Tuple[str, FrozenSet[str]]]:
    """The ``*_KEYS`` table a validator checks against: (name, keys)."""
    for node in ast.walk(vfn):
        if isinstance(node, ast.Name) and node.id.endswith("_KEYS"):
            got = _named_set(server, node.id)
            if got is not None:
                return node.id, got[0]
    return None


def _has_completeness_check(vfn, keys_name: str) -> bool:
    """``set(x) != KEYS`` anywhere in the validator body."""
    for node in ast.walk(vfn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], ast.NotEq):
            sides = (node.left, node.comparators[0])
            has_set = any(isinstance(s, ast.Call) and
                          _dotted(s.func) == ("set",) for s in sides)
            has_keys = any(isinstance(s, ast.Name) and s.id == keys_name
                           for s in sides)
            if has_set and has_keys:
                return True
    return False


def _client_ctx_keys(project: Project, client,
                     param: str) -> Optional[FrozenSet[str]]:
    """Keys of the dict literal the client binds to ``param`` (e.g.
    ``health_ctx = {"r": ..., "st": ...}``); for a param with no local
    dict (the trace context rides in from tracing), the union of dict
    keys returned by any in-project ``flow_out``."""
    keys: Set[str] = set()
    found = False
    for node in ast.walk(client.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == param:
                    found = True
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            keys.add(k.value)
    if found:
        return frozenset(keys)
    for mod in project.modules.values():
        for qual, fn in mod.functions.items():
            if qual.split(".")[-1] != "flow_out":
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and \
                        isinstance(node.value, ast.Dict):
                    found = True
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            keys.add(k.value)
    return frozenset(keys) if found else None


def run(project: Project):
    client = _find_module(project, "kvstore")
    server = _find_module(project, "kvstore_server")
    if client is None or server is None:
        return []
    findings = []

    # -- command sets ------------------------------------------------------
    sent = _client_cmds(client)
    handled = _server_cmds(server)
    for cmd in sorted(set(sent) - set(handled)):
        findings.append(Finding(
            CODE, client.rel, sent[cmd],
            "client sends cmd %r but the server never compares against it "
            "— every such RPC fails with unknown-command" % cmd,
            "cmd-unhandled:%s" % cmd))
    for cmd in sorted(set(handled) - set(sent)):
        findings.append(Finding(
            CODE, server.rel, handled[cmd],
            "server handles cmd %r but no client call site sends it — "
            "dead wire surface (or the sender was renamed)" % cmd,
            "cmd-dead:%s" % cmd))

    # -- wrapper keys: pack vs parse --------------------------------------
    mapping, pack_keys, pack_line = _pack_mapping(server)
    allowed = _parse_allowed(server)
    if pack_keys and allowed is not None:
        allowed_keys, allowed_line = allowed
        for key in sorted(pack_keys - allowed_keys):
            findings.append(Finding(
                CODE, server.rel, pack_line,
                "_pack_payload writes wrapper key %r that _parse_payload "
                "rejects as unknown — every frame carrying it is dropped"
                % key, "pack-parse-drift:%s" % key))
        for key in sorted(allowed_keys - pack_keys):
            findings.append(Finding(
                CODE, server.rel, allowed_line,
                "_parse_payload allows wrapper key %r that _pack_payload "
                "never writes — dead allowance widens the wire surface"
                % key, "pack-parse-drift:%s" % key))

    # -- context key sets + validator completeness ------------------------
    for wkey, (vname, vfn) in sorted(_validators(server).items()):
        table = _validator_keys(server, vfn)
        if table is None:
            continue
        keys_name, server_keys = table
        if not _has_completeness_check(vfn, keys_name):
            findings.append(Finding(
                CODE, server.rel, vfn.lineno,
                "%s rejects unknown keys but never checks set(ctx) != %s "
                "— frames with MISSING %r fields pass validation silently"
                % (vname, keys_name, wkey),
                "incomplete-validation:%s" % vname))
        param = mapping.get(wkey)
        if param is None:
            continue
        client_keys = _client_ctx_keys(project, client, param)
        if client_keys is None:
            continue
        for key in sorted(client_keys - server_keys):
            findings.append(Finding(
                CODE, server.rel, vfn.lineno,
                "client %s carries key %r that %s rejects as unknown — "
                "every RPC with that context is a frame error"
                % (param, key, vname),
                "ctx-drift:%s:%s" % (wkey, key)))
        for key in sorted(server_keys - client_keys):
            findings.append(Finding(
                CODE, server.rel, vfn.lineno,
                "%s requires key %r that client %s never sends — "
                "completeness validation rejects every such frame"
                % (vname, key, param),
                "ctx-drift:%s:%s" % (wkey, key)))

    # -- replay-guarded op sets -------------------------------------------
    seq_ops = _named_set(client, "_SEQ_OPS")
    mutating = _named_set(server, "_MUTATING")
    if seq_ops is not None and mutating is not None and \
            seq_ops[0] != mutating[0]:
        only_c = sorted(seq_ops[0] - mutating[0])
        only_s = sorted(mutating[0] - seq_ops[0])
        findings.append(Finding(
            CODE, client.rel, seq_ops[1],
            "client _SEQ_OPS and server _MUTATING disagree "
            "(client-only: %s, server-only: %s) — replayed frames are "
            "either re-applied or never acked" % (only_c, only_s),
            "seq-ops-drift"))
    return findings
