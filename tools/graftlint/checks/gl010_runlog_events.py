"""GL010: every runlog event name is documented, and vice versa.

The run ledger (``mxnet_tpu/runlog.py``) is an append-only JSONL stream
consumed by offline tooling — the sentinel, the atlas, post-mortem
scripts.  Its schema is the set of literal event names the tree emits;
an undocumented event is invisible to ledger consumers, a documented
event nobody emits is a query that silently matches nothing.  Mirrors
GL005 (metrics registry): code side is every ``*runlog*.event("name",
...)`` call with a literal first argument, doc side is the *Runlog
events* table in ``docs/observability.md``.  Diffed both directions.

Dynamic event names (non-literal first arg) are flagged too: the ledger
contract is only checkable when names are literals, and every current
emitter keeps them literal on purpose.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Tuple

from ..core import Finding, Project, _dotted

CODE = "GL010"
TITLE = "runlog events: emitted names match the documented table"

_EVENT_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_SECTION_RE = re.compile(r"^#+\s+.*runlog events", re.IGNORECASE)


def emitted_events(project: Project) -> Tuple[Dict[str, Tuple[str, int]],
                                              list]:
    """({event name: (rel, line)} of literal emits, [(rel, line, reason)]
    dynamic emits)."""
    events: Dict[str, Tuple[str, int]] = {}
    dynamic = []
    for mod in project.modules.values():
        in_runlog = mod.name == "runlog" or mod.name.endswith(".runlog")
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            if not chain or chain[-1] != "event":
                continue
            recv = chain[-2] if len(chain) >= 2 else None
            if not (recv in ("_runlog", "runlog") or
                    (in_runlog and recv in ("log", "self", None))):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if _EVENT_RE.match(arg.value):
                    events.setdefault(arg.value, (mod.rel, node.lineno))
                else:
                    dynamic.append((mod.rel, node.lineno,
                                    "malformed literal %r" % arg.value))
            elif not in_runlog:
                # runlog.py's own forwarding shims are parameterized by
                # design; everywhere else the name must be a literal
                dynamic.append((mod.rel, node.lineno, "non-literal name"))
    return events, dynamic


def documented_events(text: str) -> Dict[str, int]:
    """{event name: doc line} from the table under the *Runlog events*
    heading (rows until the next heading)."""
    out: Dict[str, int] = {}
    inside = False
    for i, line in enumerate(text.splitlines(), start=1):
        s = line.strip()
        if _SECTION_RE.match(s):
            inside = True
            continue
        if inside and s.startswith("#"):
            break
        if not inside or not s.startswith("| `"):
            continue
        m = re.match(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|", s)
        if m:
            out.setdefault(m.group(1), i)
    return out


def run(project: Project):
    docs_path = Path(project.config.get(
        "observability_md", project.root / "docs" / "observability.md"))
    findings = []
    rel_docs = docs_path
    try:
        rel_docs = docs_path.relative_to(project.root)
    except ValueError:
        pass

    events, dynamic = emitted_events(project)
    for rel, line, reason in dynamic:
        findings.append(Finding(
            CODE, rel, line,
            "runlog event with %s — ledger consumers cannot be checked "
            "against dynamic event names; use a literal" % reason,
            "dynamic-event:%s:%d" % (rel, line)))
    if not events:
        return findings

    doc_text = docs_path.read_text(encoding="utf-8") \
        if docs_path.exists() else ""
    doc = documented_events(doc_text)
    if not doc:
        findings.append(Finding(
            CODE, str(rel_docs), 1,
            "no 'Runlog events' table found in %s but the tree emits %d "
            "runlog events — add the section (rows: | `name` | emitted "
            "by | meaning |)" % (rel_docs, len(events)),
            "missing-events-table"))
        return findings

    for name in sorted(set(events) - set(doc)):
        rel, line = events[name]
        findings.append(Finding(
            CODE, rel, line,
            "runlog event %r is emitted here but has no row in the "
            "Runlog events table in %s" % (name, rel_docs),
            "undocumented-event:%s" % name))
    for name in sorted(set(doc) - set(events)):
        findings.append(Finding(
            CODE, str(rel_docs), doc[name],
            "runlog event %r is documented but nothing in the tree emits "
            "it — dead doc row" % name,
            "ghost-event:%s" % name))
    return findings
