"""GL005: every telemetry metric name matches docs/observability.md.

Generalizes the old ``tests/test_health.py`` import-based metric lint:
instead of importing a hand-maintained module list and reading the live
registry, this statically scans EVERY ``telemetry.counter / gauge /
histogram`` registration with a literal name across the tree and diffs
against the metric tables in ``docs/observability.md`` — both directions.
An undocumented metric is invisible to operators; a documented-but-gone
metric breaks their dashboards silently.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from ..core import Finding, Project, _INSTRUMENT_CTORS, _dotted

CODE = "GL005"
TITLE = "metric registry: code metric names == docs/observability.md"

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _code_metrics(project: Project):
    """{metric_name: (rel, line)} for literal-name registrations."""
    out = {}
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            if not chain or chain[-1] not in _INSTRUMENT_CTORS:
                continue
            telem = False
            if len(chain) == 1:
                src = mod.from_imports.get(chain[0])
                telem = bool(src) and "telemetry" in (src[0] + src[1])
            else:
                telem = "telemetry" in chain[0].lower()
                if not telem:
                    canon = project.canonical(mod, chain) or ""
                    telem = "telemetry" in canon
            if not telem:
                continue
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                name = node.args[0].value
                if _NAME_RE.match(name) and "_" in name:
                    out.setdefault(name, (mod.rel, node.lineno))
    return out


def _doc_metrics(path: Path):
    """{metric_name: line} from markdown table rows (first cell).  The
    *Runlog events* section documents ledger event names, not metrics —
    that table belongs to GL010 and is skipped here."""
    out = {}
    if not path.exists():
        return None
    in_events = False
    for i, line in enumerate(path.read_text(encoding="utf-8").splitlines(),
                             start=1):
        stripped = line.strip()
        if stripped.startswith("#"):
            in_events = bool(re.match(r"^#+\s+.*runlog events",
                                      stripped, re.IGNORECASE))
            continue
        if in_events or not stripped.startswith("| `"):
            continue
        first_cell = stripped.split("|")[1]
        for name in re.findall(r"`([^`]+)`", first_cell):
            if _NAME_RE.match(name):
                out.setdefault(name, i)
    return out


def run(project: Project):
    docs_path = Path(project.config.get(
        "observability_md", project.root / "docs" / "observability.md"))
    code = _code_metrics(project)
    docs = _doc_metrics(docs_path)
    findings = []
    if docs is None:
        findings.append(Finding(
            CODE, str(docs_path), 1,
            "metrics doc %s does not exist" % docs_path, "missing-docs"))
        return findings
    rel_docs = docs_path
    try:
        rel_docs = docs_path.relative_to(project.root)
    except ValueError:
        pass
    for name in sorted(set(code) - set(docs)):
        rel, line = code[name]
        findings.append(Finding(
            CODE, rel, line,
            "metric %r is registered here but not documented in %s"
            % (name, rel_docs), "undocumented:%s" % name))
    for name in sorted(set(docs) - set(code)):
        findings.append(Finding(
            CODE, str(rel_docs), docs[name],
            "metric %r is documented but no registration with that name "
            "exists in the tree" % name, "ghost:%s" % name))
    return findings
