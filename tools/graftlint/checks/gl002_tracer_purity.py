"""GL002: no host side effects inside traced functions.

Functions handed to ``jax.jit`` / ``custom_vjp`` / ``pallas_call`` /
``shard_map`` (and everything they call) execute ONCE at trace time, then
never again: a ``time.*`` stamp, ``np.random`` draw, telemetry bump,
``print`` or environ read there records a constant into the program and
silently stops firing per step.  ``.asnumpy()`` inside a trace either
fails on tracers or forces a device sync at trace time.

Environ reads are exempted for roots that have a declaration mechanism
(registered ops with ``env_keys``, step-program modules using
``STEP_ENV_KEYS``) — those are GL001's domain.
"""
from __future__ import annotations

from ..core import Finding, Project, fn_qual
from ..dataflow import function_env_reads

CODE = "GL002"
TITLE = "tracer purity: no host side effects reachable from traced code"

_TIME_OK = ()  # every time.* call is trace-hostile


def run(project: Project):
    findings = []
    seen = set()

    roots = []
    env_exempt_ids = set()
    step_mods = {mod.name for mod in project.modules.values()
                 if any("STEP_ENV_KEYS" in ln for ln in mod.lines)}

    for kind, mod, fnode, line in project.jit_roots():
        roots.append((kind, mod, fnode))
        if mod.name in step_mods:
            env_exempt_ids.add(id(fnode))
    for mod, op_name, env_keys, fn, line in project.registered_ops():
        roots.append(("op:%s" % op_name, mod, fn))
        env_exempt_ids.add(id(fn))

    def emit(f: Finding, root_desc: str):
        if f.fingerprint in seen:
            return
        seen.add(f.fingerprint)
        findings.append(f)

    for kind, mod, root in roots:
        root_desc = "%s root %s" % (kind, fn_qual(root))
        for g in project.reachable([root]):
            scope = getattr(g, "_gl", None)
            if scope is None:
                continue
            gmod = scope.mod
            gq = fn_qual(g)
            facts = project.facts(g)
            for b in facts.bumps:
                emit(Finding(
                    CODE, gmod.rel, b.line,
                    "telemetry bump %s.%s fires at trace time, not per "
                    "call (reached from %s) — the metric silently freezes "
                    "after the first trace" % (b.instrument,
                                               b.metric or "?", root_desc),
                    "bump:%s:%s" % (gq, b.metric or b.instrument)),
                    root_desc)
            if id(root) not in env_exempt_ids:
                for er in function_env_reads(project, g):
                    emit(Finding(
                        CODE, gmod.rel, er.line,
                        "environ read %s inside traced code (reached from "
                        "%s) is baked in at trace time and has no cache-key "
                        "declaration mechanism here"
                        % (repr(er.key) if er.key else "(dynamic)",
                           root_desc),
                        "env:%s:%s" % (gq, er.key or "dynamic")),
                        root_desc)
            for site in facts.calls:
                if site.is_ref or not site.chain:
                    continue
                canon = site.canon or ""
                last = site.chain[-1]
                bad = None
                if last == "asnumpy":
                    bad = ("asnumpy", ".asnumpy() forces a host sync and "
                           "fails on tracers")
                elif canon == "time" or canon.startswith("time."):
                    bad = ("time", "time.* reads the host clock once at "
                           "trace time")
                elif canon.startswith("numpy.random") or \
                        site.chain[:2] == ("np", "random"):
                    bad = ("np.random", "np.random draws once at trace "
                           "time — use the op's jax PRNG key")
                elif site.chain == ("print",):
                    bad = ("print", "print fires at trace time only — use "
                           "jax.debug.print for per-call output")
                if bad is not None:
                    kind_, why = bad
                    emit(Finding(
                        CODE, gmod.rel, site.line,
                        "%s in %s (reached from %s)" % (why, gq, root_desc),
                        "%s:%s" % (kind_, gq)),
                        root_desc)
    return findings
