"""GL011: never invoke user callbacks while holding a lock.

A callback invoked under a lock inherits that lock's critical section:
if the callback (user code, by definition unknowable) blocks, every
other thread contending the lock stalls; if it re-enters the owning
object, a non-reentrant lock deadlocks on the spot.  The tree's own
convention is snapshot-then-fire — collect the callback list and any
payload under the lock, release, then invoke (see
``SloScheduler._fire_level_change``).  This check walks the shared lock
model and flags calls made with a non-empty held-lock set whose callee
is callback-shaped: a name matching ``*_callback`` / ``*_hook`` /
``on_*`` / ``*cb`` etc., or a bare name bound by iterating a
callback/hook/listener container — and that does NOT resolve to an
in-project function (resolvable callees are already walked
transitively, so their lock behaviour is analysed for real rather than
assumed hostile).
"""
from __future__ import annotations

from ..core import Finding, Project
from ..dataflow import lock_analysis

CODE = "GL011"
TITLE = "lock-callback discipline: no callbacks invoked under a lock"


def run(project: Project):
    findings = []
    seen = set()
    for rel, line, qual, chain_str, held in \
            lock_analysis(project).callback_calls:
        lid = held[-1]
        fp = "callback:%s:%s:%s" % (qual, chain_str, lid)
        if fp in seen:
            continue
        seen.add(fp)
        findings.append(Finding(
            CODE, rel, line,
            "callback %s() invoked in %s while holding %s — snapshot the "
            "callback list under the lock, release, then fire (the "
            "callback can block or re-enter and take the critical "
            "section hostage)"
            % (chain_str, qual,
               " -> ".join(held) if len(held) > 1 else lid),
            fp))
    return findings
