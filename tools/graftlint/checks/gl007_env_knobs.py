"""GL007: every ``MXNET_*`` env knob is documented in docs/knobs.md.

The tree reads ~80 distinct ``MXNET_*`` environment variables; an
undocumented knob is invisible to operators, a documented-but-gone knob
is a config file that silently stopped working, and a doc default that
drifted from the code is worse than no doc at all.  This check extracts
every literal ``MXNET_*`` read (``os.environ.get`` / ``os.getenv`` /
``os.environ[...]`` / ``get_env`` / keys routed through any keyed
accessor the env-taint pass resolves) with its default and owning
module, and diffs against the generated table in ``docs/knobs.md``:

- a read with no table row  -> **undocumented** knob;
- a table row with no read  -> **ghost** knob (dead doc, or the read was
  deleted without regenerating);
- a row whose default or module list differs from the code -> **drift**.

The table is generated — ``python -m tools.graftlint --write-knobs``
rewrites the block between the ``knobs:begin``/``knobs:end`` markers,
preserving the hand-written description column by knob name — so fixing
any of the three findings is one command plus a review of the diff.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, _dotted
from ..dataflow import env_taint

CODE = "GL007"
TITLE = "env-knob registry: MXNET_* reads match docs/knobs.md"

KNOBS_BEGIN = "<!-- knobs:begin -->"
KNOBS_END = "<!-- knobs:end -->"

_KNOB_RE = re.compile(r"^MXNET_[A-Z0-9_]+$")
_SIMPLE_STR = re.compile(r"^[A-Za-z0-9_./:+-]*$")


class Knob:
    __slots__ = ("key", "sites", "defaults", "dtypes")

    def __init__(self, key):
        self.key = key
        self.sites: List[Tuple[str, int, str]] = []   # (rel, line, module)
        self.defaults: set = set()
        self.dtypes: set = set()


def _render_default(node, mod, project) -> str:
    if node is None:
        return "unset"
    if isinstance(node, ast.Constant):
        v = node.value
        if v is None:
            return "unset"
        if isinstance(v, str):
            return v if v and _SIMPLE_STR.match(v) else repr(v)
        return repr(v)
    got = project.const_str(mod, None, node)
    if got is not None:
        return got if _SIMPLE_STR.match(got) else repr(got)
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is py3.9+
        text = "<expr>"
    return "computed: %s" % (text[:40] + ("…" if len(text) > 40 else ""))


def _call_default(call: ast.Call, key_index: int):
    """(default node or None, dtype node or None) of an env-read call."""
    default = None
    dtype = None
    if len(call.args) > key_index + 1:
        default = call.args[key_index + 1]
    if len(call.args) > key_index + 2:
        dtype = call.args[key_index + 2]
    for kw in call.keywords:
        if kw.arg == "default":
            default = kw.value
        elif kw.arg == "dtype":
            dtype = kw.value
    return default, dtype


def collect_env_knobs(project: Project) -> Dict[str, Knob]:
    """Every literal MXNET_* read in the project, with defaults/types.
    Cached per project (the CLI generate path and the check share it)."""
    cached = getattr(project, "_gl_env_knobs", None)
    if cached is not None:
        return cached
    knobs: Dict[str, Knob] = {}

    def add(key, mod, line, default_s, dtype_s):
        if not _KNOB_RE.match(key):
            return
        k = knobs.setdefault(key, Knob(key))
        k.sites.append((mod.rel, line, mod.name))
        k.defaults.add(default_s)
        if dtype_s:
            k.dtypes.add(dtype_s)

    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                chain = _dotted(node.func)
                if not chain:
                    continue
                canon = project.canonical(mod, chain) or ""
                is_get = (canon in ("os.environ.get", "os.getenv") or
                          chain[-2:] == ("environ", "get") or
                          (chain[-2:] == ("environ", "setdefault") and
                           ("os" in chain or "environ" in canon)))
                is_get_env = chain[-1] == "get_env"
                if not (is_get or is_get_env):
                    continue
                if not node.args:
                    continue
                key = project.const_str(mod, None, node.args[0])
                if key is None and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    key = node.args[0].value
                if key is None:
                    # class-const key (scope-less const_str misses those)
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        for (_, cname), v in mod.class_consts.items():
                            if cname == arg.id and isinstance(v, str):
                                key = v
                                break
                if key is None:
                    continue
                dflt, dtyp = _call_default(node, 0)
                dtype_s = None
                if is_get_env:
                    dtype_s = "str"
                    if dtyp is not None:
                        dc = _dotted(dtyp)
                        if dc:
                            dtype_s = dc[-1]
                add(key, mod, node.lineno,
                    _render_default(dflt, mod, project), dtype_s)
            elif isinstance(node, ast.Subscript):
                if not isinstance(node.ctx, ast.Load):
                    continue
                chain = _dotted(node.value)
                canon = project.canonical(mod, chain) if chain else None
                if canon == "os.environ" or \
                        (chain and chain[-2:] == ("os", "environ")):
                    key = project.const_str(mod, None, node.slice)
                    if key is not None:
                        add(key, mod, node.lineno, "required", None)

    # keys routed through custom keyed accessors (beyond get_env itself)
    taint = env_taint(project)
    for mod in project.modules.values():
        for fn in mod.functions.values():
            for er in taint.extra_reads(fn):
                if er.key is not None and _KNOB_RE.match(er.key) and \
                        er.key not in knobs:
                    add(er.key, mod, er.line, "unset", None)
    project._gl_env_knobs = knobs  # type: ignore[attr-defined]
    return knobs


def knob_rows(project: Project) -> List[Tuple[str, str, str, str]]:
    """(knob, default, type, modules) rows, sorted by knob name."""
    rows = []
    for key, k in sorted(collect_env_knobs(project).items()):
        default = " / ".join(sorted(k.defaults))
        dtype = " / ".join(sorted(k.dtypes)) if k.dtypes else "str"
        mods = ", ".join(sorted({m for _, _, m in k.sites}))
        rows.append((key, default, dtype, mods))
    return rows


_HEADER = """# Environment knobs

Every ``MXNET_*`` environment variable read anywhere in ``mxnet_tpu/``
or ``tools/``.  **Generated** — the table between the markers is written
by ``python -m tools.graftlint --write-knobs`` and verified by lint
check GL007 (see [lint.md](lint.md)): undocumented reads, ghost rows and
default drift all fail the lint.  The *description* column is
hand-written and preserved across regeneration; everything else comes
from the code.

Defaults are the literal fallbacks at the read sites (`unset` = no
default / feature off, `required` = the read raises when missing,
multiple values mean different call sites use different fallbacks).

Subsystem guides: [observability.md](observability.md),
[serving.md](serving.md), [parallel.md](parallel.md),
[lint.md](lint.md).
"""


def render_knobs_md(project: Project,
                    existing_text: Optional[str]) -> str:
    """Full docs/knobs.md text: regenerate the marked table, preserving
    any hand-written description cells and all text outside markers."""
    descriptions: Dict[str, str] = {}
    before, after = _HEADER + "\n", "\n"
    if existing_text:
        for key, desc in _parse_doc_rows(existing_text).items():
            descriptions[key] = desc[3]
        if KNOBS_BEGIN in existing_text and KNOBS_END in existing_text:
            before = existing_text.split(KNOBS_BEGIN)[0]
            after = existing_text.split(KNOBS_END, 1)[1]
    lines = [KNOBS_BEGIN,
             "| knob | default | type | read in | description |",
             "|---|---|---|---|---|"]
    for key, default, dtype, mods in knob_rows(project):
        lines.append("| `%s` | `%s` | %s | %s | %s |"
                     % (key, default, dtype, mods,
                        descriptions.get(key, "")))
    lines.append(KNOBS_END)
    return before + "\n".join(lines) + after


def _parse_doc_rows(text: str) -> Dict[str, Tuple[int, str, str, str]]:
    """{knob: (line, default, modules, description)} from the marked
    table."""
    out: Dict[str, Tuple[int, str, str, str]] = {}
    inside = False
    for i, line in enumerate(text.splitlines(), start=1):
        s = line.strip()
        if s == KNOBS_BEGIN:
            inside = True
            continue
        if s == KNOBS_END:
            inside = False
            continue
        if not inside or not s.startswith("| `"):
            continue
        cells = [c.strip() for c in s.strip("|").split("|")]
        if len(cells) < 4:
            continue
        m = re.match(r"^`([^`]+)`$", cells[0])
        if not m or not _KNOB_RE.match(m.group(1)):
            continue
        default = cells[1].strip("`")
        mods = cells[3]
        desc = cells[4] if len(cells) > 4 else ""
        out.setdefault(m.group(1), (i, default, mods, desc))
    return out


def run(project: Project):
    docs_path = Path(project.config.get(
        "knobs_md", project.root / "docs" / "knobs.md"))
    findings = []
    rel_docs = docs_path
    try:
        rel_docs = docs_path.relative_to(project.root)
    except ValueError:
        pass
    if not docs_path.exists():
        findings.append(Finding(
            CODE, str(rel_docs), 1,
            "knobs doc %s does not exist — generate it with "
            "python -m tools.graftlint --write-knobs" % rel_docs,
            "missing-docs"))
        return findings
    doc = _parse_doc_rows(docs_path.read_text(encoding="utf-8"))
    code = {key: (default, mods)
            for key, default, _, mods in knob_rows(project)}

    for key in sorted(set(code) - set(doc)):
        knob = collect_env_knobs(project)[key]
        rel, line, _ = knob.sites[0]
        findings.append(Finding(
            CODE, rel, line,
            "env knob %r is read here but has no row in %s — run "
            "--write-knobs and describe it" % (key, rel_docs),
            "undocumented:%s" % key))
    for key in sorted(set(doc) - set(code)):
        findings.append(Finding(
            CODE, str(rel_docs), doc[key][0],
            "env knob %r is documented but no read of it exists in the "
            "tree — dead doc row (or a dead knob was deleted; run "
            "--write-knobs)" % key, "ghost:%s" % key))
    for key in sorted(set(doc) & set(code)):
        line, ddefault, dmods, _ = doc[key]
        cdefault, cmods = code[key]
        if ddefault != cdefault:
            findings.append(Finding(
                CODE, str(rel_docs), line,
                "env knob %r documents default `%s` but the code's is "
                "`%s` — run --write-knobs" % (key, ddefault, cdefault),
                "default-drift:%s" % key))
        elif dmods != cmods:
            findings.append(Finding(
                CODE, str(rel_docs), line,
                "env knob %r documents read-in modules %r but the code "
                "reads it from %r — run --write-knobs"
                % (key, dmods, cmods), "module-drift:%s" % key))
    return findings
