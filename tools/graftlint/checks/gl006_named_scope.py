"""GL006: raw ``jax.named_scope`` only at the atlas choke points.

The Program Atlas (docs/observability.md "Atlas") attributes fused-program
instructions to layers by the ``jax.named_scope`` names the runtime opens
at a handful of central choke points — the registry op-apply wrapper, the
executor plan/segment loops, and the optimizer/grad-sync stages of the
step-program builders.  An op or layer opening its OWN scope nests inside
(or collides with) the choke-point scope and corrupts the attribution:
the innermost token wins, so the rogue scope silently steals every
instruction under it.  This check flags any ``jax.named_scope`` call in
the runtime tree outside the allowlisted choke-point modules; new scope
vocabulary belongs in :mod:`mxnet_tpu.atlas`, not at op definitions.
"""
from __future__ import annotations

import ast

from ..core import Finding, Project, _dotted

CODE = "GL006"
TITLE = "atlas scope discipline: jax.named_scope only at the choke points"

#: modules allowed to open scopes — the documented choke points (plus the
#: atlas itself, which owns the naming contract)
DEFAULT_ALLOWLIST = (
    "mxnet_tpu/atlas.py",
    "mxnet_tpu/ops/registry.py",
    "mxnet_tpu/executor.py",
    "mxnet_tpu/fused_step.py",
    "mxnet_tpu/fused.py",
    "mxnet_tpu/optimizer.py",
)


def _is_jax_named_scope(mod, chain):
    """True when a dotted call chain resolves to jax's named_scope."""
    if not chain or chain[-1] != "named_scope":
        return False
    if len(chain) == 1:
        src = mod.from_imports.get("named_scope")
        return bool(src) and (src[0] == "jax" or src[0].startswith("jax."))
    head = chain[0]
    target = mod.imports.get(head)
    if target is not None:
        return target == "jax" or target.startswith("jax.")
    src = mod.from_imports.get(head)
    if src is not None:
        full = ".".join(p for p in src if p)
        return full == "jax" or full.startswith("jax.")
    # unresolvable head: conservative only for the canonical spellings
    return head in ("jax", "_jax")


def _enclosing(mod, lineno):
    """Innermost function qualname containing ``lineno`` (or <module>)."""
    best, best_line = None, -1
    for qual, node in mod.functions.items():
        start = getattr(node, "lineno", None)
        end = getattr(node, "end_lineno", None)
        if start is None or end is None:
            continue
        if start <= lineno <= end and start > best_line:
            best, best_line = qual, start
    return best or "<module>"


def run(project: Project):
    allow = set(project.config.get("named_scope_allowlist",
                                   DEFAULT_ALLOWLIST))
    findings = []
    for mod in project.modules.values():
        if mod.rel in allow:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            if not chain or not _is_jax_named_scope(mod, chain):
                continue
            where = _enclosing(mod, node.lineno)
            findings.append(Finding(
                CODE, mod.rel, node.lineno,
                "raw jax.named_scope outside the atlas choke points "
                "(corrupts per-layer attribution; see docs/observability.md "
                "'Atlas' — scopes belong to the registry/executor/step-"
                "builder wrappers)",
                "raw-named-scope:%s.%s" % (mod.name, where)))
    return findings
