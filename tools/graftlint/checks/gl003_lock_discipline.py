"""GL003: lock-order and blocking-under-lock discipline.

Runs over the shared interprocedural lock model in
:mod:`tools.graftlint.dataflow` (one :class:`LockAnalysis` per project,
reused by GL011 and the ``--dump-lock-graph`` export / runtime
sanitizer).  The model builds the lock-acquisition graph over every
``threading.Lock`` / ``RLock`` / ``Condition`` site in the tree
(``with`` statements plus a transitive walk through resolvable callees,
local aliases like ``lk = self._lock`` included).  Two findings:

- **order**: lock pair acquired in both orders somewhere in the tree — a
  potential ABBA deadlock.
- **blocking**: a blocking call (``block_until_ready``, ``asnumpy``,
  socket ``recv``/``accept``, zero-arg ``queue.get()`` without timeout,
  ``time.sleep``, zero-arg ``join()``) made while holding a
  telemetry/engine/serving/health lock — those locks sit on hot paths
  (every metric bump, every engine push, every serving request) and must
  never wait on the device or the network.

Lock identity is static: ``module.Class.attr`` for instance locks,
``module.name`` for module globals, an anonymous family id for locks
created dynamically (dict-of-locks).  ``Condition(lock)`` aliases the
wrapped lock; ``Condition.wait`` releases it, so ``wait`` is deliberately
not in the blocking set.  Unresolvable lock expressions are skipped,
never guessed.
"""
from __future__ import annotations

from ..core import Finding, Project
from ..dataflow import lock_analysis

CODE = "GL003"
TITLE = "lock discipline: consistent order, no blocking under hot locks"


def run(project: Project):
    an = lock_analysis(project)

    findings = list(an.blocking_findings)
    # deduplicate blocking findings (same site reached via several callers)
    uniq = {}
    for f in findings:
        uniq.setdefault(f.fingerprint, f)
    findings = list(uniq.values())

    reported = set()
    for (a, b), (rel, line, qual) in sorted(an.edges.items()):
        if (b, a) not in an.edges:
            continue
        pair = tuple(sorted((a, b)))
        if pair in reported:
            continue
        reported.add(pair)
        rel2, line2, qual2 = an.edges[(b, a)]
        findings.append(Finding(
            CODE, rel, line,
            "inconsistent lock order: %s -> %s in %s (%s:%d) but "
            "%s -> %s in %s (%s:%d) — potential ABBA deadlock"
            % (a, b, qual, rel, line, b, a, qual2, rel2, line2),
            "order:%s<->%s" % pair))
    return findings
